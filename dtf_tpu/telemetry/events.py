"""The fleet EVENT PLANE — one structured, durable timeline per run.

PRs 11–19 each grew an ad-hoc trail (controller.jsonl, health transition
deques, swap log lines, stream WARNs, sink stats); this module is the ONE
log they all write so ``python -m dtf_tpu.telemetry timeline`` can answer
"what happened to the run" across train/fault/serve/swap/stream. The
on-disk contract is the serve-log sink's, reused verbatim:

- ``events-00000.jsonl`` … — one event per line, framed
  ``"<crc32c:08x> <body>"`` by the SHARED record codec
  (:func:`dtf_tpu.data.stream.servelog.encode_record` — both planes damage
  and recover identically);
- ``EVENTS_MANIFEST.json`` — the atomic commit point (``atomic_replace``):
  a shard enters it only once rotated or flushed. A crash mid-rotation
  (the ``crash_in_event_rotate`` chaos verb) leaves a fully-written shard
  the next :class:`EventLog` over the directory ADOPTS; shard names are
  never reused. Distinct basenames mean an event log and a serve-log sink
  can share a directory without colliding.

Every record is ``{"event": kind, "seq": n, "t": wall, **fields}``: ``seq``
is the writer's monotone emit counter (the causal tiebreak when wall
stamps collide), ``t`` the injectable wall clock — an emitter holding its
own wall stamp (the fault controller) passes ``t=`` and wins, so the
timeline's ordering is the emitters' own causal story, not the sink's.

Emission must never take a run down: ``emit`` swallows ``OSError`` (and
counts it in :meth:`stats`); only the injected rotation crash propagates,
because that IS the scenario under test. Zero device readbacks by
construction — every field is a host int/float/str the caller already
holds (counter-proven in tests/test_events.py). jax-free at module level
(the telemetry srclint fence); reads are non-mutating
(:func:`read_events` never adopts) so the timeline tool can run against a
live run's directory. docs/OBSERVABILITY.md §9 is the schema walk.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import List, Optional

from dtf_tpu._hostio import append_line, atomic_replace
from dtf_tpu.fault.inject import InjectedCrash
from dtf_tpu.data.stream.servelog import (MANIFEST_VERSION, decode_record,
                                          encode_record)

log = logging.getLogger("dtf_tpu")

#: the event plane's atomic commit point (distinct from the serve-log
#: sink's SERVELOG_MANIFEST.json so the two can share a directory).
EVENTS_MANIFEST_BASENAME = "EVENTS_MANIFEST.json"

#: shard naming — index-ordered, prefix-distinct from ``shard-*.jsonl``.
EVENT_SHARD_FMT = "events-%05d.jsonl"


def event_shard_name(index: int) -> str:
    return EVENT_SHARD_FMT % int(index)


def events_manifest_path(events_dir: str) -> str:
    return os.path.join(events_dir, EVENTS_MANIFEST_BASENAME)


def read_events_manifest(events_dir: str) -> Optional[dict]:
    """The committed-shard list, or None (fresh dir, or one that crashed
    before its first rotation — adoption/readers handle orphans)."""
    try:
        with open(events_manifest_path(events_dir)) as f:
            manifest = json.load(f)
    except (FileNotFoundError, ValueError):
        return None
    if int(manifest.get("version", -1)) != MANIFEST_VERSION:
        raise ValueError(
            f"event manifest version {manifest.get('version')!r} != "
            f"{MANIFEST_VERSION} under {events_dir!r}")
    return manifest


def _on_disk_shards(events_dir: str) -> List[str]:
    try:
        return sorted(n for n in os.listdir(events_dir)
                      if n.startswith("events-") and n.endswith(".jsonl"))
    except FileNotFoundError:
        return []


class EventLog:
    """Size-rotated structured event writer over one directory (module
    docstring). One writer per directory per process (``append_line`` is
    single-writer); a Router fleet SHARES one — the pump is one thread
    and records carry their replica/subsystem fields."""

    def __init__(self, events_dir: str, *, rotate_bytes: int = 1 << 16,
                 wall=time.time):
        self.dir = os.fspath(events_dir)
        self.rotate_bytes = int(rotate_bytes)
        #: injectable wall clock (the host pass's clock-escape fence;
        #: deterministic-timeline tests pin it)
        self.wall = wall
        #: emit/flush are called from the main thread AND producer threads
        #: (the stream tier emits from its prefetch thread) — seq/shard
        #: state updates under one lock
        self._lock = threading.Lock()
        manifest = read_events_manifest(self.dir)
        self._shards: list = list(manifest["shards"]) if manifest else []
        self._adopted = self._adopt_orphans()
        #: next shard index after everything on disk — committed or
        #: orphaned — so a crashed rotation's name is never reused.
        self._shard_index = self._next_index()
        self._seq = 0
        self._open_records = 0
        self._open_bytes = 0
        self._rotations = 0
        self._io_errors = 0
        #: chaos seams (install_serve_fault): damage the N-th record's
        #: CRC / crash after the N-th rotation's shard is durable but
        #: BEFORE its manifest commit.
        self._corrupt_at: Optional[int] = None
        self._crash_rotate_at: Optional[int] = None
        self._fault_note = None
        self._injected_corrupt = 0

    # ----------------------------------------------------------- recovery

    def _adopt_orphans(self) -> int:
        """Fold fully-written shards a crashed rotation left uncommitted
        back into the manifest; record counts re-derived from CRC-valid
        lines (the serve-log sink's discipline)."""
        committed = {s["name"] for s in self._shards}
        adopted = 0
        for name in _on_disk_shards(self.dir):
            if name in committed:
                continue
            n = self._count_records(os.path.join(self.dir, name))
            self._shards.append({"name": name, "records": n})
            adopted += 1
            log.warning(
                "event log %s: adopted orphan shard %s (%d events) — a "
                "previous writer crashed between the shard write and its "
                "manifest commit; committed events are never lost",
                self.dir, name, n)
        if adopted:
            self._shards.sort(key=lambda s: s["name"])
            self._commit_manifest()
        return adopted

    @staticmethod
    def _count_records(path: str) -> int:
        with open(path) as f:
            return sum(1 for line in f.read().split("\n")
                       if line and decode_record(line) is not None)

    def _next_index(self) -> int:
        idx = [int(n[len("events-"):-len(".jsonl")])
               for n in _on_disk_shards(self.dir)
               if n[len("events-"):-len(".jsonl")].isdigit()]
        return max(idx) + 1 if idx else 0

    # ------------------------------------------------------------ writing

    def emit(self, kind: str, /, **fields) -> dict:
        """Append one event. ``fields`` are host facts the caller already
        holds; a caller-supplied ``t`` overrides the sink's wall stamp
        (the emitter's own causal clock wins), ``event``/``seq`` never do.
        Returns the record (tests assert on it). Never raises on IO — an
        observability sink must not take the run down — except for the
        injected rotation crash, which IS the scenario under test."""
        with self._lock:
            rec = {"event": str(kind), "seq": self._seq,
                   "t": round(self.wall(), 6)}
            fields.pop("event", None)
            fields.pop("seq", None)
            rec.update(fields)
            self._seq += 1
            line = encode_record(rec)
            if (self._corrupt_at is not None
                    and rec["seq"] == self._corrupt_at):
                # flip the CRC nibbles: the body survives, the frame
                # fails — readers must take the deterministic skip
                # branch (bit rot)
                self._corrupt_at = None
                self._injected_corrupt += 1
                crc_hex, _, body = line.partition(" ")
                line = f"{int(crc_hex, 16) ^ 0xFFFFFFFF:08x} {body}"
                self._note("corrupt_event_record")
            try:
                append_line(
                    os.path.join(self.dir,
                                 event_shard_name(self._shard_index)),
                    line)
            except OSError:
                self._io_errors += 1
                return rec
            self._open_records += 1
            self._open_bytes += len(line) + 1
            if self.rotate_bytes and self._open_bytes >= self.rotate_bytes:
                self._rotate()
            return rec

    def _rotate(self) -> None:
        """Commit the open shard and start the next one. The shard bytes
        are already durable; the manifest replace IS the commit point, so
        the injected crash lands between the two and the next mount's
        adoption must recover."""
        self._shards.append({"name": event_shard_name(self._shard_index),
                             "records": self._open_records})
        rotation = self._rotations
        self._rotations += 1
        self._shard_index += 1
        self._open_records = 0
        self._open_bytes = 0
        if (self._crash_rotate_at is not None
                and rotation == self._crash_rotate_at):
            self._crash_rotate_at = None
            self._note("crash_in_event_rotate")
            raise InjectedCrash(
                f"injected crash mid-rotation of event shard "
                f"{self._shards[-1]['name']} (the shard is durable; the "
                "manifest commit never ran — adoption must recover it)")
        self._commit_manifest()

    def _commit_manifest(self) -> None:
        try:
            atomic_replace(events_manifest_path(self.dir), json.dumps({
                "version": MANIFEST_VERSION,
                "shards": self._shards,
                "records": int(sum(s["records"] for s in self._shards)),
            }, indent=1, sort_keys=True))
        except OSError:
            self._io_errors += 1

    def flush(self) -> None:
        """Commit the open shard (if it holds events) so a reader sees
        everything emitted so far without needing orphan recovery."""
        with self._lock:
            if self._open_records:
                self._rotate()

    def close(self) -> None:
        self.flush()

    # -------------------------------------------------------------- chaos

    def arm_corrupt(self, nth: int, note=None) -> None:
        """Damage the CRC of the event with ``seq == nth`` (writer
        lifetime) — readers must skip it deterministically."""
        self._corrupt_at = int(nth)
        self._fault_note = note

    def arm_crash_rotate(self, nth: int, note=None) -> None:
        """``crash_in_event_rotate@N``: raise after the N-th rotation's
        shard is durable but before its manifest commit (0-based)."""
        self._crash_rotate_at = int(nth)
        self._fault_note = note

    def _note(self, what: str) -> None:
        if self._fault_note is not None:
            self._fault_note(what)

    # -------------------------------------------------------------- stats

    def stats(self) -> dict:
        """Host counters for launcher JSON lines (zero device work)."""
        return {
            "events": self._seq,
            "shards_committed": len(self._shards),
            "open_records": self._open_records,
            "rotations": self._rotations,
            "adopted_shards": self._adopted,
            "io_errors": self._io_errors,
            "injected_corrupt": self._injected_corrupt,
        }


def read_events(events_dir: str, *,
                include_orphans: bool = True) -> List[dict]:
    """Every decodable event under ``events_dir``, in causal order —
    committed shards in manifest order first, then (by default) orphan
    shards in name order, each shard's lines in write order. NON-MUTATING:
    never adopts, never commits — safe against a LIVE run's directory
    (the timeline tool's read path). CRC-damaged lines are dropped
    deterministically (same bytes → same drops on every read)."""
    manifest = read_events_manifest(events_dir)
    names = [s["name"] for s in manifest["shards"]] if manifest else []
    if include_orphans:
        committed = set(names)
        names += [n for n in _on_disk_shards(events_dir)
                  if n not in committed]
    out: List[dict] = []
    for name in names:
        try:
            with open(os.path.join(events_dir, name)) as f:
                raw = f.read()
        except OSError:
            continue
        for line in raw.split("\n"):
            if not line:
                continue            # the torn/empty tail line
            rec = decode_record(line)
            if rec is not None:
                out.append(rec)
    return out


__all__ = ["EVENTS_MANIFEST_BASENAME", "EventLog", "event_shard_name",
           "events_manifest_path", "read_events", "read_events_manifest"]
