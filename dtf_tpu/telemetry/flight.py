"""Crash flight recorder + stall watchdog — the postmortem half of telemetry.

The axon-tunnel hangs that ate rounds 3–4 (CLAUDE.md) die with nothing on
disk: the host loop blocks inside a device call and the run's last N steps
of context evaporate with the process. The flight recorder keeps those N
steps in a host-side ring — step number, wall timestamp, per-phase
durations, host RSS, the last hook-materialized scalars — and dumps them
as ONE JSON line (the bench.py contract) on crash, stall, or SIGTERM, plus
nothing at all in the steady state.

Deliberate constraint: the dump path touches NO device API. A postmortem
fires exactly when the backend is wedged; a ``device.memory_stats()`` call
from the watchdog thread would hang the postmortem the same way the step
hung the loop (the CLAUDE.md "never probe a dead tunnel in-process" rule).
Host RSS + host timings are what we can always have.

The stall watchdog is a daemon thread: if no step completes within
``max(min_stall_s, factor × p99 recent step time)``, it dumps a
``stall`` postmortem (once per stall episode — a completing step re-arms
it). It detects the hang; it does not try to recover it (relaunch is the
cluster manager's job, resume is the checkpointer's).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Mapping, Optional

from dtf_tpu._hostio import append_line, atomic_replace
from dtf_tpu.metrics import quantile


def _rss_mb() -> Optional[float]:
    try:
        import resource

        # linux ru_maxrss is KB
        return round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0, 1)
    except Exception:
        return None


class FlightRecorder:
    """Ring buffer of the last ``keep`` step records + postmortem dumps.

    ``path`` is the postmortem file; each dump appends one JSON line (a
    stall dump followed by a crash dump both survive). ``clock``/``wall``
    are injectable for deterministic tests.
    """

    def __init__(self, path: Optional[str] = None, *, keep: int = 64,
                 heartbeat_path: Optional[str] = None,
                 clock=time.monotonic, wall=time.time):
        self.path = path
        self.keep = keep
        #: liveness file for the elastic run controller (dtf_tpu/fault):
        #: written atomically by the stall watchdog's poll thread — NOT by
        #: the hot path — with the last completed step and the stalled
        #: flag. None = no heartbeat (the default for bare recorders).
        self.heartbeat_path = heartbeat_path
        self.clock = clock
        self.wall = wall
        self.records: collections.deque = collections.deque(maxlen=keep)
        self.last_scalars: dict = {}
        self.last_step_t: Optional[float] = None   # clock() domain
        self.dumps = 0
        #: postmortem context providers (add_provider): host-fact callables
        #: merged into every dump under "context" — the serve tier
        #: registers its in-flight request ids + per-slot ages here. The
        #: NO-device-API constraint extends to providers: they run while
        #: the backend may be wedged, so host state only.
        self._providers: dict[str, object] = {}
        # REENTRANT: the SIGTERM postmortem handler runs dump() on the
        # main thread between bytecodes — if the signal lands inside
        # record_step's critical section (every step), a plain Lock would
        # self-deadlock the handler against its own thread and make the
        # process immune to SIGTERM. RLock lets the same-thread dump
        # proceed (the in-flight record is in a consistent-enough state:
        # deque.append is atomic under the GIL).
        self._lock = threading.RLock()

    # ------------------------------------------------------------ recording

    def record_step(self, step: int, durations: Mapping[str, float]) -> None:
        """One completed loop iteration — host facts only (a device value
        here would be a blocking readback in the hot path)."""
        rec = {"step": step, "t": round(self.wall(), 3)}
        rec.update({k: round(v, 6) for k, v in durations.items()})
        rss = _rss_mb()
        if rss is not None:
            rec["rss_mb"] = rss
        with self._lock:
            self.records.append(rec)
            self.last_step_t = self.clock()

    def note_scalars(self, step: int, scalars: Mapping[str, float]) -> None:
        """Last metrics a hook chose to materialize (LoggingHook feeds this
        at its own cadence) — the loss the postmortem can report without
        the recorder ever blocking on a device value itself."""
        with self._lock:
            self.last_scalars = {"step": int(step),
                                 **{k: float(v) for k, v in scalars.items()}}

    def step_durations_s(self) -> list:
        """Recent whole-iteration durations (for the stall threshold)."""
        with self._lock:
            return [r["step_s"] for r in self.records if "step_s" in r]

    def add_provider(self, name: str, fn) -> None:
        """Register a postmortem context provider: ``fn() -> dict`` of
        HOST facts (no device API — it runs against a possibly-wedged
        backend), merged into every dump under ``context[name]``. A
        provider that raises is reported as its error string instead of
        masking the postmortem (dump() never raises). Re-registering a
        name replaces it; ``fn=None`` removes it."""
        with self._lock:
            if fn is None:
                self._providers.pop(name, None)
            else:
                self._providers[name] = fn

    # ------------------------------------------------------------ heartbeat

    def write_heartbeat(self, *, stalled: bool = False,
                        extra: Optional[Mapping] = None) -> None:
        """One atomic liveness record (tmp + rename so the controller can
        never read a torn write). Host facts only, never raises — it runs
        on the watchdog thread against a possibly-wedged backend. A wedged
        loop keeps heartbeating (the thread is alive) with ``stalled:
        true`` and a frozen ``step`` — exactly the signature the
        controller's run-wedged verdict keys on; a SIGKILL'd host simply
        stops writing. ``extra`` merges caller facts into the record —
        the serve tier's :class:`dtf_tpu.serve.client.Heartbeat` stamps
        its fleet panel (completed/queue/quarantines) here so a serving
        process exposes the same liveness surface as a trainer."""
        path = self.heartbeat_path
        if not path:
            return
        with self._lock:
            step = self.records[-1]["step"] if self.records else None
        rec = {"t": round(self.wall(), 3), "pid": os.getpid(),
               "step": step, "stalled": bool(stalled)}
        if extra:
            rec.update(extra)
        try:
            atomic_replace(path, json.dumps(rec))
        except OSError:
            pass

    # ----------------------------------------------------------------- dump

    def dump(self, reason: str, extra: Optional[Mapping] = None) -> dict:
        """Append one postmortem JSON line; returns the record. Never
        raises — the dump path runs inside except/signal/watchdog contexts
        where a secondary failure would mask the primary one."""
        with self._lock:
            post = {
                "telemetry": "postmortem",
                "reason": reason,
                "t": round(self.wall(), 3),
                "pid": os.getpid(),
                "n_records": len(self.records),
                "records": list(self.records),
                "last_scalars": dict(self.last_scalars),
            }
            rss = _rss_mb()
            if rss is not None:
                post["rss_mb"] = rss
            if self._providers:
                ctx = {}
                for name, fn in self._providers.items():
                    try:
                        ctx[name] = fn()
                    except Exception as e:  # noqa: BLE001 — a provider
                        # failure must not mask the primary postmortem
                        ctx[name] = {"provider_error": repr(e)[:200]}
                post["context"] = ctx
            if extra:
                post.update(extra)
            self.dumps += 1
        if self.path:
            try:
                append_line(self.path, json.dumps(post))
            except OSError:
                pass
        return post


class StallWatchdog:
    """Daemon thread: dump a ``stall`` postmortem when no step completes
    inside the adaptive threshold (see module docstring).

    ``check(now)`` holds all the logic and is called directly by tests;
    the thread just polls it. One dump per stall episode: a new step
    completion re-arms the trigger.
    """

    def __init__(self, flight: FlightRecorder, *, factor: float = 10.0,
                 min_stall_s: float = 60.0, poll_s: float = 1.0,
                 on_stall=None):
        self.flight = flight
        self.factor = factor
        self.min_stall_s = min_stall_s
        self.poll_s = poll_s
        self.on_stall = on_stall     # extra callback (tests, launchers)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._fired_at: Optional[float] = None   # last_step_t when dumped

    def threshold_s(self) -> float:
        # p99 of recent iteration times, not the median: in the sync-free
        # loop most iterations are ms-scale dispatches while the periodic
        # readback/eval/checkpoint iterations run seconds-to-minutes — a
        # median-based bar would flag every such legitimate pause as a
        # stall. The first long pause of a run is only covered by
        # min_stall_s: set it above the longest expected hook pause.
        slow = quantile(self.flight.step_durations_s(), 0.99)
        return max(self.min_stall_s,
                   self.factor * slow if slow is not None else 0.0)

    def check(self, now: Optional[float] = None) -> bool:
        """True when a stall postmortem was dumped by THIS call."""
        last = self.flight.last_step_t
        if last is None:           # nothing completed yet: startup/compile
            return False
        if self._fired_at == last:
            return False           # already reported this episode
        now = self.flight.clock() if now is None else now
        waited = now - last
        thresh = self.threshold_s()
        if waited < thresh:
            return False
        self._fired_at = last
        post = self.flight.dump("stall", {
            "stalled_for_s": round(waited, 3),
            "stall_threshold_s": round(thresh, 3)})
        if self.on_stall is not None:
            try:
                self.on_stall(post)
            except Exception:
                pass
        return True

    # ------------------------------------------------------------ lifecycle

    def stalled_now(self) -> bool:
        """True while the current stall episode is unresolved (fired and
        no step has completed since)."""
        return (self._fired_at is not None
                and self._fired_at == self.flight.last_step_t)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        # first heartbeat BEFORE the first poll interval: the controller's
        # startup-timeout clock stops the moment liveness appears, and
        # compile time shouldn't eat into it
        self.flight.write_heartbeat(stalled=False)

        def run():
            while not self._stop.wait(self.poll_s):
                self.check()
                # liveness every poll: a wedged loop keeps heartbeating
                # with stalled=true (this thread is alive even when the
                # main thread is stuck inside a device call); only a dead
                # process goes silent
                self.flight.write_heartbeat(stalled=self.stalled_now())

        self._thread = threading.Thread(
            target=run, name="dtf-stall-watchdog", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
