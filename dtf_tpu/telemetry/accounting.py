"""MFU / goodput accounting — "what fraction of the hardware are we using,
and what fraction of the wall clock actually trained?".

MFU follows the two conventions the benches already bank (scripts/
bench_lm.py, PERF.md §1):

- **analytic**: 6 FLOPs per parameter per token (fwd+bwd weight FLOPs)
  plus the attention term ``12·L·d·s`` per token — the "Scalable Training
  of Language Models using JAX pjit and TPUv4" (arxiv 2204.06514)
  accounting, comparable across papers;
- **XLA cost analysis**: the AOT ``compiled.cost_analysis()`` flops of the
  actual program (the bench_cost_table.py idiom) — a LOWER bound (scan
  bodies counted once, Pallas custom calls report zero).

Goodput = productive step wall time / total run wall time, with the
non-productive remainder attributed to named buckets (compile, checkpoint,
eval, logging, restore, data_wait, h2d, other) — the run-level accounting
the TPU-pod scaling literature reports runs by. Bucket seconds come from
host timers only (the trainer's per-hook timing plus jax.monitoring's
compile-duration events); nothing here reads a device value.
"""

from __future__ import annotations

from typing import Mapping, Optional

#: TPU v5e peak bf16 matmul throughput per chip (the bench.py constant).
V5E_PEAK_BF16_FLOPS = 197e12

#: ResNet-50 v1.5 @224 fwd ≈ 4.09e9 MAC-derived FLOPs/image, training ≈ 3×
#: fwd (the bench.py constant — keep the two in sync; bench.py cannot
#: import this module because its parent process never imports jax deps).
RESNET50_TRAIN_FLOPS_PER_IMG = 3 * 4.09e9

#: goodput buckets the trainer/hook instrumentation feeds; anything else
#: lands in "other" so the report always sums to the measured overhead.
GOODPUT_BUCKETS = ("compile", "checkpoint", "eval", "logging", "restore",
                   "data_wait", "h2d", "hooks", "profile", "preempt_sync",
                   "other")

#: buckets that are BACKPRESSURE, not lost time: in the sync-free loop the
#: host blocks inside LoggingHook's metrics readback (and generic hooks)
#: precisely while the DEVICE works through the dispatched step queue —
#: charging that wait as overhead would invert goodput on healthy runs
#: (report ~0.1 while the device is ~99% busy). h2d is the async transfer
#: dispatch overlapping compute. preempt_sync is PreemptionHook's periodic
#: multi-host flag allgather — a device readback that absorbs the host's
#: accumulated run-ahead exactly like LoggingHook's metrics readback (the
#: rare preemption-save it also covers is once-per-dying-run noise). These
#: are reported per-bucket but excluded from the productive-time
#: subtraction.
BACKPRESSURE_BUCKETS = ("logging", "hooks", "h2d", "preempt_sync")


def param_count(params) -> int:
    """Total parameter count from array METADATA only (``x.size`` never
    materializes a value, so this is safe on live training state)."""
    import jax

    return int(sum(x.size for x in jax.tree.leaves(params)))


def analytic_lm_flops_per_step(*, n_params: int, layers: int, width: int,
                               seq_len: int, tokens_per_step: int) -> float:
    """Full-step (fwd+bwd) FLOPs for a dense transformer LM step —
    ``(6·N + 12·L·d·s) · tokens`` (the bench_lm.py mfu_analytic model)."""
    return float(6 * n_params + 12 * layers * width * seq_len) \
        * tokens_per_step


def cost_analysis_flops(fn, *args) -> Optional[float]:
    """Best-effort AOT flops of ``fn(*args)`` (bench_cost_table idiom).

    Returns None when the backend/program offers no cost analysis. NOTE:
    lowering here is a fresh trace of ``fn`` — callers that pin trace
    counts (the compile fence) must account for it or prefer the analytic
    path.
    """
    try:
        # chipless cost analysis of a caller-owned program — no
        # aot-ok: fence/pins/donation decision is being made here
        cost = fn.lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = float(cost.get("flops", 0.0))
        return flops or None
    except Exception:
        return None


class GoodputTracker:
    """Accumulates overhead seconds into named buckets.

    ``account(bucket, seconds)`` from anywhere on the host (trainer hook
    timing, checkpoint restore, compile-duration events). Unknown bucket
    names fold into ``other`` — the report must always reconcile.
    """

    def __init__(self):
        self.buckets: dict[str, float] = {}

    def account(self, bucket: str, seconds: float) -> None:
        if bucket not in GOODPUT_BUCKETS:
            bucket = "other"
        self.buckets[bucket] = self.buckets.get(bucket, 0.0) + seconds

    def report(self, total_s: float) -> Mapping[str, float]:
        """``{goodput, productive_s, <bucket>_s...}`` for ``total_s`` of
        wall clock. Productive = total − Σ overheads, clamped at 0, where
        overhead EXCLUDES the :data:`BACKPRESSURE_BUCKETS` (the host's
        wait on device compute — see their note). Remaining bucket times
        can still overlap the async device timeline (a compile inside the
        first dispatch), so the subtraction is an upper bound on lost
        time, i.e. goodput is conservative on short runs."""
        overhead = sum(s for b, s in self.buckets.items()
                       if b not in BACKPRESSURE_BUCKETS)
        productive = max(total_s - overhead, 0.0)
        out = {"goodput": round(productive / total_s, 4) if total_s else 0.0,
               "productive_s": round(productive, 3),
               "total_s": round(total_s, 3)}
        for name, s in sorted(self.buckets.items()):
            out[f"{name}_s"] = round(s, 3)
        return out
