"""Weight publishing — the train→serve hot-swap transport (ISSUE 14).

ROADMAP item 4's scenario: a model that retrains continuously while
serving heavy traffic. The trainer side emits params-only snapshots every
K steps into a **publish dir**; the serving side polls it and rolls new
versions across the fleet with zero downtime
(:meth:`dtf_tpu.serve.router.Router.start_swap`). This module is the
transport between them, built on three invariants:

- **Atomic versioned manifest.** A publish is (1) an Orbax params-only
  save under ``<dir>/<version>/params`` (``Checkpointer.save_params`` —
  Orbax's own tmp+rename makes the step dir atomic), (2) a content digest
  of the written files, (3) one ``PUBLISH_MANIFEST.json`` replacing the
  previous via tmp + ``os.replace``. A crash ANYWHERE before step (3)
  leaves the previous manifest — and therefore the previous version —
  fully intact (the ``crash_in_publish`` chaos verb lands between (2) and
  (3), the widest window, and tests/test_serve_swap.py proves the old
  version still serves).
- **Monotone versions.** Versions are a counter independent of the train
  step (a retrain from step 0 still publishes version N+1); the manifest
  records ``version -> {step, digest}`` history so readers can fall back
  past a corrupt newest version with a WARN (the ``restore`` contract:
  guarded walk for "latest", NO fallback for an explicitly requested
  version).
- **Content digest.** ``dir_digest`` hashes every file of the version dir
  (name + bytes); :class:`PublishWatcher` verifies it before handing
  params to a swap, so a truncated/garbled publish is SKIPPED with a WARN
  and the fleet keeps serving the version it already has — corruption
  never reaches a live replica.

``PublishHook`` (:mod:`dtf_tpu.hooks`) drives :class:`ParamPublisher`
from the training loop; :class:`PublishWatcher` is the serve-side poller
(``scripts/serve_gpt.py --publish_dir`` wires it to the Router's rolling
swap). docs/RESILIENCE.md §9 walks the end-to-end contract.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import time
from typing import Any, Optional

from dtf_tpu._hostio import atomic_replace
from dtf_tpu.checkpoint import Checkpointer

PyTree = Any

log = logging.getLogger("dtf_tpu")

MANIFEST_BASENAME = "PUBLISH_MANIFEST.json"

#: manifest history entries retained (>= the Checkpointer's max_to_keep,
#: so every on-disk version has a recorded digest to verify against).
HISTORY_KEEP = 8


def dir_digest(path: str) -> str:
    """Content digest of every regular file under ``path`` (sorted
    relpath + raw bytes) — the publish integrity check. Chunked reads so
    large param files never land in memory whole."""
    h = hashlib.sha256()
    for root, dirs, files in os.walk(path):
        dirs.sort()
        for name in sorted(files):
            fp = os.path.join(root, name)
            h.update(os.path.relpath(fp, path).encode())
            h.update(b"\0")
            try:
                with open(fp, "rb") as f:
                    for chunk in iter(lambda: f.read(1 << 20), b""):
                        h.update(chunk)
            except OSError:
                h.update(b"<unreadable>")
            h.update(b"\0")
    return "sha256:" + h.hexdigest()


def read_manifest(directory: str) -> Optional[dict]:
    """The publish manifest, or None (no publish yet / unreadable file —
    callers WARN and fall back to the on-disk version walk)."""
    path = os.path.join(os.fspath(directory), MANIFEST_BASENAME)
    try:
        with open(path) as f:
            return json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, json.JSONDecodeError, ValueError) as e:
        log.warning("unreadable publish manifest %s (%s)", path, e)
        return None


class ParamPublisher:
    """Trainer-side publisher: params-only snapshots + atomic manifest.

    One per run, chief-process only for the manifest (the Orbax save is
    collective — every process calls :meth:`publish`, each writes its own
    shards, and only process 0 computes the digest and flips the
    manifest). ``keep`` bounds on-disk versions (Orbax prunes; the
    manifest history keeps digests for everything still on disk).
    """

    def __init__(self, directory: str, *, keep: int = 3, wall=time.time):
        self.directory = os.fspath(directory)
        #: injectable wall clock for the manifest's ``published_t`` stamp
        #: (replay-stable publish tests pin it; the host pass's
        #: clock-escape fence is why it is a parameter, not a call)
        self.wall = wall
        os.makedirs(self.directory, exist_ok=True)
        self._ckpt = Checkpointer(self.directory, max_to_keep=keep,
                                  async_save=False)
        m = read_manifest(self.directory)
        on_disk = [int(d) for d in os.listdir(self.directory)
                   if d.isdigit()]
        # never REUSE a version number with a dir on disk: a crashed
        # publish leaves an uncommitted dir whose bytes are the OLD
        # attempt's — re-saving under the same number would no-op (Orbax
        # dedupes existing steps) and the manifest would then commit a
        # version whose content is not what was just published. Readers
        # only trust manifest-committed versions, so the orphan dir is
        # inert garbage Orbax's max_to_keep eventually prunes.
        self._next_version = max(int(m["version"]) if m else 0,
                                 max(on_disk, default=0)) + 1
        #: test/chaos seam: called AFTER the version data is durable and
        #: BEFORE the manifest flips — the widest crash window atomicity
        #: has to cover (``crash_in_publish`` raises here; the previous
        #: manifest must keep serving).
        self._pre_commit = None
        self.published = 0
        #: optional fleet EventLog (ISSUE 20) — each committed publish
        #: lands on the run timeline.
        self.event_log = None

    @property
    def checkpointer(self) -> Checkpointer:
        return self._ckpt

    def publish(self, step: int, params: PyTree) -> int:
        """Publish ``params`` as the next version; returns the version.

        Sequence (the atomicity contract, module docstring): durable
        params-only save → digest → manifest tmp+rename. Any failure
        before the rename leaves the previous version intact; the failed
        attempt's dir (if any) is an UNCOMMITTED orphan readers never
        trust — the next publish takes a fresh number (never reuses a
        number with bytes on disk, see ``__init__``)."""
        import jax

        version = self._next_version
        # consume the number NOW: a crash below must not let the next
        # publish reuse a version whose dir may hold this attempt's bytes
        self._next_version = version + 1
        self._ckpt.save_params(version, params, force=True)
        self._ckpt.wait()
        if jax.process_index() != 0:
            return version
        digest = dir_digest(os.path.join(self.directory, str(version)))
        if self._pre_commit is not None:
            self._pre_commit(version, step)
        old = read_manifest(self.directory) or {}
        history = dict(old.get("history") or {})
        history[str(version)] = {"step": int(step), "digest": digest}
        for v in sorted(history, key=int)[:-HISTORY_KEEP]:
            del history[v]
        manifest = {"schema": 1, "version": version, "step": int(step),
                    "digest": digest, "published_t": round(self.wall(), 3),
                    "history": history}
        path = os.path.join(self.directory, MANIFEST_BASENAME)
        # THE commit point — atomic (tmp + os.replace inside the choke
        # point; a crash anywhere above leaves the old manifest serving)
        atomic_replace(path, json.dumps(manifest, indent=1,
                                        sort_keys=True) + "\n")
        self.published += 1
        if self.event_log is not None:
            # after the manifest rename: only COMMITTED versions reach
            # the timeline (a crashed attempt never published anything)
            self.event_log.emit("publish_version", version=version,
                                step=int(step), digest=digest)
        log.info("published params version %d (train step %d) to %s",
                 version, step, self.directory)
        return version

    def close(self) -> None:
        self._ckpt.close()


def _known_digest(manifest: Optional[dict], version: int) -> Optional[str]:
    if not manifest:
        return None
    if int(manifest.get("version", -1)) == version:
        return manifest.get("digest")
    return (manifest.get("history") or {}).get(str(version), {}).get("digest")


def load_published(directory: str,
                   version: Optional[int] = None) -> tuple[int, int, PyTree]:
    """Restore published params: ``(version, train_step, params)``.

    ``version=None`` is the guarded walk (``Checkpointer.restore``
    parity): the manifest's newest version is verified against its digest
    and restored; a corrupt/unreadable version WARNs and falls back to
    the next older on-disk version, raising only when nothing is
    servable. An EXPLICIT version gets no fallback — digest mismatch or
    restore failure raises, because the caller asked for exactly that
    version (the ``restore(step=...)`` contract, ISSUE 14 satellite)."""
    directory = os.fspath(directory)
    manifest = read_manifest(directory)
    # closed before returning: a long-running swap watcher calls this per
    # observed publish, and each Orbax manager owns threads/handles that
    # would otherwise accumulate for the life of the server
    ckpt = Checkpointer(directory)

    def try_one(v: int, explicit: bool) -> tuple[int, int, PyTree]:
        want = _known_digest(manifest, v)
        if want is not None:
            got = dir_digest(os.path.join(directory, str(v)))
            if got != want:
                raise ValueError(
                    f"published version {v} at {directory} fails its "
                    f"digest check ({got[:23]}... != {want[:23]}...) — "
                    "corrupt publish")
        elif explicit:
            log.warning(
                "published version %d at %s has no recorded digest "
                "(manifest pruned/unreadable); restoring unverified", v,
                directory)
        params = ckpt.restore_params(step=v)
        step = int((manifest or {}).get("history", {})
                   .get(str(v), {}).get("step", -1))
        if v == int((manifest or {}).get("version", -2)):
            step = int(manifest["step"])
        return v, step, params

    try:
        if version is not None:
            return try_one(int(version), explicit=True)
        on_disk = {int(d) for d in os.listdir(directory) if d.isdigit()}
        if manifest:
            # only manifest-COMMITTED versions are candidates: a dir the
            # manifest never named is an uncommitted orphan (a crash between
            # save and rename) whose bytes were never vouched for
            known = {int(manifest["version"])} | \
                {int(v) for v in (manifest.get("history") or {})}
            versions = sorted(known & on_disk, reverse=True)
        else:
            versions = sorted(on_disk, reverse=True)
            if versions:
                log.warning(
                    "no publish manifest under %s; walking %d on-disk "
                    "version(s) UNVERIFIED", directory, len(versions))
        if not versions:
            raise FileNotFoundError(f"no published version under {directory}")
        last_err: Optional[Exception] = None
        for i, v in enumerate(versions):
            try:
                return try_one(v, explicit=False)
            except Exception as e:  # noqa: BLE001 — any unreadable-version
                # class falls back (the guarded-restore contract)
                last_err = e
                older = versions[i + 1] if i + 1 < len(versions) else None
                log.warning(
                    "published version %d at %s is unservable (%s: %.200s); "
                    "falling back to %s", v, directory, type(e).__name__, e,
                    f"version {older}" if older is not None
                    else "nothing — no older version")
        raise RuntimeError(
            f"every published version under {directory} is unservable "
            f"(tried {versions}); last error: "
            f"{type(last_err).__name__}: {last_err}")
    finally:
        ckpt.close()


class PublishWatcher:
    """Serve-side poller over a publish dir (module docstring).

    :meth:`load_new` is the swap driver's one call: None when there is
    nothing new, else ``(version, step, params)`` for a version newer
    than the last applied — digest-verified, with corrupt publishes
    SKIPPED once with a WARN (the fleet keeps serving what it has; the
    version is remembered so a wedged publish cannot re-WARN every
    poll). Mark :meth:`note_applied` after the rolling swap completes so
    a rolled-back version can be retried by a later republish only.
    """

    def __init__(self, directory: str, *, applied_version: int = 0):
        self.directory = os.fspath(directory)
        self.applied_version = applied_version
        self.skipped: set[int] = set()

    def manifest(self) -> Optional[dict]:
        return read_manifest(self.directory)

    def poll(self) -> Optional[dict]:
        """The manifest, iff it names a version newer than the last
        applied and not already skipped as corrupt."""
        m = self.manifest()
        if not m:
            return None
        v = int(m.get("version", 0))
        if v <= self.applied_version or v in self.skipped:
            return None
        return m

    def load_new(self) -> Optional[tuple[int, int, PyTree]]:
        m = self.poll()
        if m is None:
            return None
        v = int(m["version"])
        try:
            return load_published(self.directory, version=v)
        except Exception as e:  # noqa: BLE001 — a corrupt publish must
            # not take serving down: skip it, keep the current version
            self.skipped.add(v)
            log.warning(
                "skipping published version %d at %s (%s: %.200s); the "
                "fleet keeps serving its current version", v,
                self.directory, type(e).__name__, e)
            return None

    def note_applied(self, version: int) -> None:
        self.applied_version = max(self.applied_version, int(version))


__all__ = ["MANIFEST_BASENAME", "ParamPublisher", "PublishWatcher",
           "dir_digest", "load_published", "read_manifest"]
