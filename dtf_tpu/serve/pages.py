"""Block-granular prefix KV page cache — prefill shared prompt stems ONCE.

Shared prompt stems (system prompts, few-shot headers) are the dominant
redundant work in production serving: every request re-runs the same
transformer prefill over the same leading tokens. This module is the reuse
layer the Gemma-on-TPU serving comparison (PAPERS.md, arxiv 2605.25645)
names as the first lever: the engine keeps a device-resident **page pool**
— fixed-size windows of KV (every batch-led cache leaf, int8 scales
included, one page id spanning ALL layers) — and this host-side
:class:`PrefixIndex` maps *whole prefixes* to chains of pages.

Design rules (the fixed-shape discipline of docs/SERVING.md, extended):

- A page covers token positions ``[i*page_size, (i+1)*page_size)`` of a
  request that started at position 0 — positions are absolute, so RoPE'd
  K/V is bit-reusable by any request whose prefix TOKENS match exactly.
- The cache key is the token-hash of the **entire prefix** through the
  page (KV at position t depends on every token <= t, so a page keyed by
  only its own tokens would alias different contexts); lookups verify the
  stored token tuple exactly — a hash collision can never serve wrong KV.
- Entries form parent chains (the page for prefix length ``2p`` holds a
  ref on the page for length ``p``), and in-flight requests pin the chain
  they are loading — eviction (LRU) only ever takes an unpinned,
  childless entry, so a page can never be overwritten mid-copy.
- Pages are COPIED into a slot's private cache on admission (ONE compiled
  gather for the whole chain, not a transformer forward) and copied out of
  a slot after a miss prefill — decode itself never touches the pool, so
  the fenced ``gpt_serve`` decode graph is byte-identical with the cache
  on or off.
- **Save admission**: a page is only copied OUT once its prefix has been
  seen ``save_after`` times (default 2). Eagerly caching every full page
  would spend a save dispatch on each request's unique tail — pool
  pollution plus host overhead that can exceed the prefill work saved;
  the second-sighting rule caches exactly the prefixes traffic repeats.

The page pool doubles as the **requeue KV transport** of the serve
resilience tier (docs/RESILIENCE.md "Serving"): when the Router drains a
quarantined replica, each re-admitted request goes through ordinary
admission on its survivor — a cached stem re-prefills as ONE page gather
and only the uncached tail replays through the transformer. The drain
itself releases the dead replica's in-flight pins
(``Scheduler.evict_for_requeue``), so its pool pages become evictable
instead of leaking; ``DecodeEngine.prefix_stats()["pinned"]`` is the
leak tripwire.

The device half (pool state + the two AOT page programs) lives in
``engine.py``; :func:`pool_abstract` here builds the pool's abstract
struct from the engine's cache struct so the two cannot desynchronize.
It is also the HBM fit planner's pricing hook (``python -m
dtf_tpu.analysis fit --config=gpt_serve``): per-page device bytes come
from ``pool_abstract(cache, 1, page_size, mesh)`` at the REAL model
config, so the planner's page-pool answer is derived from the exact
struct the engine allocates (``engine.engine_state_struct`` is the
per-slot twin).
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Any, Callable, Optional, Sequence

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from dtf_tpu.models import gpt

PyTree = Any


def pool_abstract(cache_struct: PyTree, n_pages: int, page_size: int,
                  mesh=None) -> PyTree:
    """Abstract page-pool tree derived from the engine's cache struct:
    every batch-led ``[S, H, L, D]`` leaf becomes ``[P, H, page, D]`` at
    the same tree path (int8 caches bring their scale leaves along
    automatically); ``cache_index`` is dropped — a page's position range
    is host bookkeeping. With ``mesh``, heads shard over ``'model'`` like
    the cache itself (page copies stay local per TP shard) while the page
    axis replicates — slots shard over ``'data'``, so the slot gather is
    the same known resharding cost as sharded prefill (docs/SERVING.md)."""
    out: dict = {}
    for path, s in jax.tree_util.tree_flatten_with_path(cache_struct)[0]:
        name = gpt._cache_leaf_name(path)
        if name in gpt._NON_BATCH_CACHE_KEYS:
            continue
        if name not in gpt._BATCH_LED_CACHE_KEYS:
            raise ValueError(f"unknown cache leaf {name!r} (see "
                             "gpt._BATCH_LED_CACHE_KEYS)")
        shape = (n_pages, s.shape[1], page_size, s.shape[3])
        sh = (NamedSharding(mesh, P(None, "model", None, None))
              if mesh is not None else None)
        gpt._set_by_path(out, path,
                         jax.ShapeDtypeStruct(shape, s.dtype, sharding=sh))
    return out


class PageStore:
    """Mountable prefix-page state: ONE device pool + ONE host
    :class:`PrefixIndex` that several engines may share.

    PR 6 gave every replica its own pool; prefill/decode disaggregation
    (docs/SERVING.md) needs the pool as a **KV transport** — a dedicated
    prefill replica saves pages that a decode replica then loads in its
    one-gather admission — which is exactly "the same store mounted by N
    engines". Updates are functional (each page program returns a fresh
    pool tree that replaces :attr:`pool`) and the pump loop is
    single-threaded, so a plain holder is the whole mechanism; on a
    multi-host fleet this object is the seam where a cross-host page DMA
    would slot in. Build one with :meth:`DecodeEngine's <build>` shapes
    via ``Router.build(prefill_replicas=...)`` or mount an engine's own
    (``engine.page_store``) into further engines (``shared_pages=``)."""

    def __init__(self, pool, index: "PrefixIndex"):
        self.pool = pool
        self.index = index


def check_pool_compatible(pool, pool_abs) -> None:
    """A shared pool must be byte-compatible with what the mounting
    engine would have allocated (same tree, shapes, dtypes) — a silent
    mismatch would gather wrong-shaped KV into a live slot."""
    import numpy as np

    got = jax.tree_util.tree_flatten_with_path(pool)[0]
    want = jax.tree_util.tree_flatten_with_path(pool_abs)[0]
    if len(got) != len(want):
        raise ValueError(
            f"shared page pool has {len(got)} leaves, engine expects "
            f"{len(want)} — different cache layout (kv dtype / GQA?)")
    for (gp, g), (wp, w) in zip(got, want):
        if gp != wp or tuple(g.shape) != tuple(w.shape) \
                or np.dtype(g.dtype) != np.dtype(w.dtype):
            raise ValueError(
                f"shared page pool leaf {gp} is {g.shape}/{g.dtype}, "
                f"engine expects {wp} {w.shape}/{w.dtype} — the engines "
                "mounting one store must be built identically")


@dataclasses.dataclass
class _Entry:
    """One cached page: ``tokens`` is the WHOLE prefix through this page
    (exact-match verification), ``refs`` counts children + live pins,
    ``epoch`` is the param VERSION whose weights produced the KV — a
    lookup only matches entries of the requesting engine's own version
    (ISSUE 14: a cached stem can never serve stale-weight KV across a
    hot-swap)."""

    page_id: int
    tokens: tuple
    parent: Optional["_Entry"]
    refs: int = 0
    last_use: int = 0
    epoch: int = 0


@dataclasses.dataclass(frozen=True)
class PrefixHandle:
    """A pinned chain of pages covering ``n_tokens`` leading prompt
    tokens, root→leaf; hold it for the lifetime of the request and
    release exactly once (the deepest entry carries the pin)."""

    entries: tuple
    n_tokens: int


class PrefixIndex:
    """Host index over the page pool: token-hash keyed, exact-verified,
    refcounted, LRU-evicting. Pure bookkeeping — never touches a device
    value (the engine runs the compiled copies).

    ``hash_fn`` is injectable so tests can force collisions and prove the
    exact-match verification actually carries correctness.
    """

    def __init__(self, n_pages: int, page_size: int, *,
                 save_after: int = 2,
                 hash_fn: Callable[[tuple], int] = hash):
        if n_pages < 1:
            raise ValueError(f"n_pages={n_pages} must be >= 1")
        if page_size < 1:
            raise ValueError(f"page_size={page_size} must be >= 1")
        if save_after < 1:
            raise ValueError(f"save_after={save_after} must be >= 1")
        self.n_pages = n_pages
        self.page_size = page_size
        self.save_after = save_after
        self._hash = hash_fn
        self._by_hash: dict[int, list[_Entry]] = {}
        self._free = list(range(n_pages))
        self._clock = 0
        #: sightings of not-yet-cached prefixes (the save-admission
        #: filter) — bounded so a long unique-prompt stream cannot grow
        #: host memory.
        self._seen: "collections.OrderedDict[tuple, int]" = (
            collections.OrderedDict())
        self._seen_cap = 16 * n_pages
        # token-level hit/miss totals live on the ENGINE's counters (one
        # writer): a second copy here would collide with them in the
        # scheduler's serve_prefix_* stats namespace and drift whenever
        # one side is reset (the bench resets engine counters at warm-up)
        self.stats = {"hits": 0, "misses": 0, "evictions": 0}

    # ------------------------------------------------------------- lookup

    def _find(self, tokens: tuple, epoch: int = 0) -> Optional[_Entry]:
        for e in self._by_hash.get(self._hash(tokens), ()):
            # exact-match verification + the param-version epoch gate: KV
            # produced by different weights is a different cache entry
            # even for identical tokens (hot-swap invariant, ISSUE 14)
            if e.tokens == tokens and e.epoch == epoch:
                return e
        return None

    def longest(self, prompt: Sequence[int],
                cap: Optional[int] = None, *,
                epoch: int = 0) -> tuple[int, Optional[_Entry]]:
        """Longest registered page chain covering a prefix of ``prompt``
        AT ``epoch`` (the caller's param version): ``(n_pages, deepest
        entry)``. ``cap`` bounds the page count (the engine caps
        admission reuse at ``(len-1)//page`` so at least one prompt token
        always runs live — the first sampled token needs the last
        position's logits)."""
        p = self.page_size
        top = len(prompt) // p if cap is None else cap
        for k in range(top, 0, -1):
            e = self._find(tuple(prompt[:k * p]), epoch)
            if e is not None:
                return k, e
        return 0, None

    def acquire(self, prompt: Sequence[int], *,
                epoch: int = 0) -> Optional[PrefixHandle]:
        """Pin the longest reusable chain for ``prompt`` at ``epoch``
        (admission-time lookup). None on a miss; on a hit the DEEPEST
        entry takes one pin (its ancestors are already held alive by
        child refs)."""
        cap = max(0, (len(prompt) - 1) // self.page_size)
        k, e = self.longest(prompt, cap=cap, epoch=epoch)
        if e is None:
            self.stats["misses"] += 1
            return None
        chain: list[_Entry] = []
        node: Optional[_Entry] = e
        while node is not None:
            chain.append(node)
            node = node.parent
        chain.reverse()
        assert len(chain) == k, (len(chain), k)
        self._clock += 1
        for n in chain:
            n.last_use = self._clock
        e.refs += 1
        self.stats["hits"] += 1
        return PrefixHandle(entries=tuple(chain),
                            n_tokens=k * self.page_size)

    def release(self, handle: PrefixHandle) -> None:
        handle.entries[-1].refs -= 1
        assert handle.entries[-1].refs >= 0

    # ------------------------------------------------------------ reserve

    def save_eligible(self, prompt: Sequence[int], have: int,
                      full: int, *, epoch: int = 0) -> int:
        """The save-admission filter: bump the sighting count of every
        not-yet-cached full-page prefix of ``prompt`` (pages ``have`` to
        ``full``) and return how many CONTIGUOUS pages from ``have`` have
        now been seen ``save_after`` times — only those are worth a save
        dispatch (a unique tail never reaches the threshold, so it costs
        nothing and pollutes nothing). Chains must stay contiguous: the
        first unpopular page stops eligibility, deeper pages just record
        their sighting."""
        p = self.page_size
        eligible, counting = 0, True
        for i in range(have, full):
            # sightings are per (epoch, prefix): pre-swap traffic must
            # not pre-qualify a prefix for the NEW version's save gate
            prefix = (epoch, tuple(prompt[:(i + 1) * p]))
            c = self._seen.pop(prefix, 0) + 1
            self._seen[prefix] = c               # re-insert = LRU refresh
            while len(self._seen) > self._seen_cap:
                self._seen.popitem(last=False)
            if counting and c >= self.save_after:
                eligible += 1
            else:
                counting = False
        return eligible

    def reserve(self, prefix: tuple, parent: Optional[_Entry], *,
                epoch: int = 0) -> Optional[_Entry]:
        """Allocate a page for ``prefix`` at ``epoch`` (registering it
        immediately) — from the free list, else by evicting the LRU
        unpinned childless entry. None when every page is pinned or
        parented (the save is skipped, never blocked). ``parent`` must be
        the entry for ``prefix`` minus one page (None for the first
        page) and of the SAME epoch — a chain can never cross a weight
        version."""
        if len(prefix) != (0 if parent is None
                           else len(parent.tokens)) + self.page_size:
            raise ValueError(
                f"prefix of {len(prefix)} tokens does not extend parent "
                f"({0 if parent is None else len(parent.tokens)}) by one "
                f"{self.page_size}-token page")
        if parent is not None and parent.epoch != epoch:
            raise ValueError(
                f"parent epoch {parent.epoch} != {epoch}: a page chain "
                "cannot mix KV from two param versions")
        if self._find(prefix, epoch) is not None:
            raise ValueError("prefix already registered; look it up "
                             "instead of reserving a duplicate page")
        if self._free:
            pid = self._free.pop()
        else:
            # `parent` may be a childless leaf (refs == 0) while the save
            # loop extends it — evicting it here would free its page id
            # into the pop() below and leave the new child holding a
            # dangling parent whose pool slot now stores DIFFERENT KV; a
            # later hit would walk that chain and serve wrong tokens.
            # Deeper ancestors are safe (child refs pin them).
            victim = min(
                (e for es in self._by_hash.values() for e in es
                 if e.refs == 0 and e is not parent),
                key=lambda e: e.last_use, default=None)
            if victim is None:
                return None
            self._evict(victim)
            pid = self._free.pop()
        self._clock += 1
        e = _Entry(pid, prefix, parent, refs=0, last_use=self._clock,
                   epoch=epoch)
        if parent is not None:
            parent.refs += 1
        self._by_hash.setdefault(self._hash(prefix), []).append(e)
        self._seen.pop((epoch, prefix), None)  # cached now — sightings done
        return e

    def invalidate_stale(self, epoch: int) -> int:
        """Free every entry whose ``epoch`` differs from the (new)
        current one — the post-swap cleanup. Lookups already epoch-gate
        (stale KV is unreachable the moment a replica's version bumps —
        the LAZY half of invalidation); this reclaims the pool bytes
        eagerly once a rolling swap completes. Runs leaf-first until a
        fixpoint (evicting a child unparents its ancestor); entries
        still pinned are left for their release + LRU (the drain path
        releases pins BEFORE the swap, so post-swap this returns with 0
        stale entries left — ``prefix_stats()['pinned']`` is the
        tripwire). Returns the number of pages freed."""
        freed = 0
        while True:
            stale = [e for es in self._by_hash.values() for e in es
                     if e.epoch != epoch and e.refs == 0]
            if not stale:
                return freed
            for e in stale:
                self._evict(e)
                freed += 1

    def _evict(self, e: _Entry) -> None:
        es = self._by_hash[self._hash(e.tokens)]
        es.remove(e)
        if not es:
            del self._by_hash[self._hash(e.tokens)]
        if e.parent is not None:
            e.parent.refs -= 1
        self._free.append(e.page_id)
        self.stats["evictions"] += 1

    # ------------------------------------------------------------- report

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_entries(self) -> int:
        return sum(len(v) for v in self._by_hash.values())

    def pinned(self) -> int:
        """Live pins across entries (children excluded) — 0 when every
        admitted request has released its handle (slot-evict contract)."""
        pins = 0
        for es in self._by_hash.values():
            for e in es:
                kids = sum(1 for fs in self._by_hash.values()
                           for f in fs if f.parent is e)
                pins += e.refs - kids
        return pins
