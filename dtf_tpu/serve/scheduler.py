"""Request scheduling over a :class:`~dtf_tpu.serve.engine.DecodeEngine`.

FIFO admission with prefill/decode interleave: each :meth:`Scheduler.tick`
runs at most ``prefill_chunks_per_tick`` prompt chunks (admitting queued
requests into free slots as chunk budget allows — a long prompt spreads its
prefill over several ticks instead of stalling everyone's decode), then one
``decode_all`` step for every occupied slot. Slots are evicted on EOS, on
``max_new``, or when the slot's ``max_len`` budget fills; the freed slot is
immediately reusable next tick — the continuous-batching loop.

Observability rides :class:`dtf_tpu.metrics.MetricWriter` (the training
stack's writer): queue depth and slot occupancy per logging interval, plus
per-request TTFT and per-token latency on completion. ``stats()`` returns
the same aggregates for benches (``scripts/serve_gpt.py`` prints them as
its one JSON line). With a :class:`dtf_tpu.telemetry.Telemetry` attached
the engine calls are additionally recorded as ``serve_prefill_chunk`` /
``serve_page_load`` / ``serve_page_save`` / ``serve_decode`` phase spans
(host wall time per compiled-program call — the training loop's
data_wait/dispatch decomposition, serving edition) plus ``router_wait``
(queue time between submit and a slot accepting the request — the
admission latency the Router SLO panel watches), and ``stats()`` gains
their p50/p99. All of it is host clock arithmetic: zero added device
readbacks (counter-instrumented test, PR 5 idiom).

With an engine built with ``prefix_pages > 0`` admission consults the
prefix page cache: the pinned page chain lands in ONE batched gather on
the same ``prefill_chunks_per_tick`` budget as prompt chunks (one budget
unit replacing ``n_cached/prefill_chunk`` chunks of transformer work),
the live chunks continue at ``start = n_cached``, new full pages scatter
back in one dispatch after the last chunk, and the pin is released on
slot evict — the refcount contract of :mod:`dtf_tpu.serve.pages`.

Resilience (ISSUE 12, docs/RESILIENCE.md "Serving"): requests can end in
a terminal status other than ``done`` —

- ``shed`` — bounded-queue admission control (``max_queue``): an
  over-full queue rejects at submit with a ``retry_after_s`` hint
  instead of growing host memory and tail latency without bound;
- ``timeout`` — per-request deadlines (``Request.ttft_deadline_s`` /
  ``deadline_s``, measured from submit on the scheduler clock) evict at
  the next tick, whether the request is still queued, mid-prefill, or
  decoding;
- ``error`` — an engine exception during ADMISSION is attributed to the
  admitting request and isolates to it (the ``poison_request`` chaos
  verb); decode-path exceptions have no single owner and propagate to
  the Router's health machinery, which quarantines the replica and
  requeues its in-flight requests (:meth:`Scheduler.evict_for_requeue`,
  status ``requeued`` on the vacated replica).

``poll`` reports the terminal status (+hint/cause fields);
:class:`RequestFailed` is what ``result()`` raises immediately instead of
spinning ``max_ticks`` on a request that will never finish.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import time
from typing import Optional, Sequence

from dtf_tpu.metrics import quantile as _quantile

log = logging.getLogger("dtf_tpu")

#: terminal statuses that are NOT success — ``result()`` raises
#: :class:`RequestFailed` on sight instead of pumping to tick exhaustion.
FAILED_STATUSES = ("shed", "timeout", "error")


class RequestFailed(RuntimeError):
    """A request ended in a terminal non-success status (``shed`` /
    ``timeout`` / ``error``). Carries the ``poll()`` payload so callers
    can honor ``retry_after_s`` without a second lookup."""

    def __init__(self, rid: int, info: dict):
        self.rid = rid
        self.status = info.get("status", "?")
        self.info = dict(info)
        hint = ""
        if "retry_after_s" in info:
            hint = f" (retry after {info['retry_after_s']}s)"
        elif info.get("timeout_kind"):
            hint = f" ({info['timeout_kind']} deadline)"
        elif info.get("error"):
            hint = f" ({info['error']})"
        super().__init__(f"request {rid} terminally {self.status}{hint}")


@dataclasses.dataclass(frozen=True)
class Request:
    """One decode request. Sampling fields mirror ``gpt.generate``;
    the deadline fields are client promises measured from submit on the
    scheduler's clock (0 = none): ``ttft_deadline_s`` bounds the wait for
    the FIRST token, ``deadline_s`` the whole request."""

    prompt: Sequence[int]
    max_new: int = 32
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_id: Optional[int] = None
    pad_id: int = 0
    seed: int = 0
    ttft_deadline_s: float = 0.0
    deadline_s: float = 0.0


@dataclasses.dataclass
class _Rec:
    rid: int
    req: Request
    #: queued | prefill | running | done | shed | timeout | error | requeued
    status: str = "queued"
    slot: int = -1
    chunks_done: int = 0
    tokens: list = dataclasses.field(default_factory=list)
    submit_t: float = 0.0
    #: None until the first token lands — NOT 0.0: an injectable test
    #: clock legitimately stamps first tokens at t == 0.0, and a falsy
    #: check would re-arm the TTFT deadline on an actively-decoding row
    first_token_t: Optional[float] = None
    #: TTFT in scheduler TICKS (submit_tick → first_token_tick): the
    #: per-replica clock. On the single-process CPU sim every replica's
    #: wall time shares one thread, so wall TTFT charges a replica for
    #: the whole fleet's work; tick counts are what a real parallel
    #: fleet's wall clock would see (the disaggregation benches/tests
    #: compare on these).
    submit_tick: int = 0
    first_token_tick: Optional[int] = None
    finish_t: float = 0.0
    #: pinned prefix-page chain (engine.prefix_match) — pages loaded so
    #: far, released on slot evict (the refcount contract).
    handle: object = None
    pages_loaded: int = 0
    #: end-to-end trace id (router-assigned global rid when behind one;
    #: the local rid otherwise) — tags every span/trace event this
    #: request touches, through scheduler and engine alike.
    trace_id: int = -1
    #: submit moment on the TraceCollector's clock (chrome ts domain)
    submit_us: float = 0.0
    retry_after_s: float = 0.0        # shed hint (poll surfaces it)
    timeout_kind: str = ""            # "ttft" | "total" on timeout
    error: str = ""                   # admission-failure cause on error
    requeued: bool = False            # re-admitted off a quarantined replica
    #: the param VERSION whose weights decoded this request (ISSUE 14),
    #: stamped at completion from the engine. Exactly ONE version per
    #: request by construction: a rolling swap DRAINS a replica before
    #: swapping it, so a request spanning the boundary replays whole on
    #: one version (tokens cleared on requeue).
    version: Optional[int] = None
    #: per-request speculative accounting (ISSUE 19): proposals the draft
    #: made for this request and how many the verifier accepted — host
    #: ints mirrored off the scheduler's fleet counters, recorded into
    #: the serve-log sink so draft distillation can weigh its examples.
    #: Cleared on requeue with the tokens (the replay regenerates both).
    proposed: int = 0
    accepted: int = 0


class Scheduler:
    """FIFO continuous-batching scheduler (see module docstring).

    ``prefill_chunks_per_tick`` bounds how much prefill work may delay the
    next decode step (0 = admit greedily, whole queue's worth per tick).
    ``clock`` is injectable for deterministic tests.
    """

    def __init__(self, engine, writer=None, *, log_every: int = 0,
                 prefill_chunks_per_tick: int = 4, clock=time.monotonic,
                 completed_cap: int = 100_000, telemetry=None,
                 ttft_slo_s: float = 0.0, max_queue: int = 0,
                 shed_retry_after_s: float = 0.25,
                 postmortem_name: Optional[str] = "serve_scheduler",
                 log_sink=None, replica_index: int = 0):
        self.engine = engine
        self.writer = writer
        #: serve-log sink (ISSUE 19): every terminal ``done`` request is
        #: recorded as future training data — host facts only, zero added
        #: device readbacks (the token ints already crossed in tick()).
        #: A Router threads ONE shared sink here with per-replica indices.
        self._log_sink = log_sink
        self.replica_index = int(replica_index)
        self.log_every = log_every
        self.telemetry = telemetry
        if telemetry is not None and postmortem_name:
            # the serve postmortem: a crash/stall/SIGTERM dump names the
            # in-flight request ids + per-slot ages (host facts only —
            # the dump path must not touch a wedged backend). The Router
            # registers ONE aggregate provider instead (postmortem_name
            # None for its replica schedulers).
            telemetry.add_postmortem_provider(
                postmortem_name, self.postmortem_state)
        #: TTFT service-level objective (0 = untracked): ``stats()`` then
        #: reports the fraction of completed first tokens inside it — the
        #: per-replica SLO rollup the router surfaces (docs/SERVING.md).
        self.ttft_slo_s = ttft_slo_s
        if prefill_chunks_per_tick < 0:
            # a negative budget would be truthy in tick()'s `or 10**9`
            # fallback yet fail `> 0` — admission silently off, replay()
            # spinning forever on a non-empty queue
            raise ValueError(
                f"prefill_chunks_per_tick={prefill_chunks_per_tick} must "
                "be >= 0 (0 = admit greedily)")
        self.prefill_chunks_per_tick = prefill_chunks_per_tick
        self.clock = clock
        if max_queue < 0:
            raise ValueError(f"max_queue={max_queue} must be >= 0 "
                             "(0 = unbounded)")
        #: bounded-queue admission control: with ``max_queue > 0`` a
        #: submit against a full queue is SHED (terminal status + a
        #: retry_after_s hint) instead of queueing forever — overload
        #: sheds load, it does not grow tail latency without bound.
        self.max_queue = max_queue
        self.shed_retry_after_s = shed_retry_after_s
        #: completed records (and latency samples) retained for poll();
        #: beyond the cap the OLDEST finished request is forgotten — a
        #: long-running server must not grow host memory per request.
        #: poll() of a forgotten id raises KeyError; callers that need a
        #: result must collect it within cap completions (or raise the cap).
        self.completed_cap = completed_cap
        self._free = list(range(engine.n_slots))
        self._queue: collections.deque[_Rec] = collections.deque()
        self._admitting: Optional[_Rec] = None
        self._running: dict[int, _Rec] = {}
        self._recs: dict[int, _Rec] = {}
        self._done_order: collections.deque[int] = collections.deque()
        self._next_id = 0
        self._tick = 0
        self._ttfts: collections.deque[float] = collections.deque(
            maxlen=completed_cap)
        #: MONOTONE count of TTFT samples ever recorded (the deque is
        #: maxlen-bounded, so ``len(_ttfts)`` stops moving once full —
        #: windowed consumers like the Router's canary SLO gate measure
        #: "samples since a mark" against this counter instead), plus a
        #: lockstep flag deque marking samples of REQUEUED requests:
        #: their TTFT honestly includes time lost on a dead replica, so
        #: the canary gate must not blame the new weights for them.
        self._ttft_count = 0
        self._ttft_requeued: collections.deque[bool] = collections.deque(
            maxlen=completed_cap)
        self._tok_lats: collections.deque[float] = collections.deque(
            maxlen=completed_cap)
        self._completed = 0
        self._occupancy_sum = 0.0
        self._queue_peak = 0
        # resilience counters (host ints — the stats()/postmortem panel)
        self._shed = 0
        self._timeouts = 0
        self._timeouts_ttft = 0
        self._request_errors = 0
        self._requeued_out = 0
        self._requeued_in = 0
        # speculative-decode acceptance over RUNNING slots only (the
        # engine's own counters also see stale still-active rows)
        self._spec_proposed = 0
        self._spec_accepted = 0
        #: acceptance bucketed by the engine's param version at proposal
        #: time (ISSUE 19): {version: [proposed, accepted]} — the
        #: per-version panel that shows a distilled draft's acceptance
        #: climbing across a draft-only swap.
        self._accept_by_version: dict[int, list] = {}
        # deadline sweeps only run once a deadlined request has been seen
        self._any_deadlines = False

    # ----------------------------------------------------------- submit/poll

    def submit(self, req: Request, *, trace_id: Optional[int] = None,
               submit_t: Optional[float] = None,
               requeued: bool = False) -> int:
        """Accept a request; returns the local rid. ``trace_id`` threads an
        end-to-end id through every span this request touches (the Router
        passes its fleet-global rid; standalone, the local rid is the id).
        ``submit_t``/``requeued`` are the Router's requeue path: a request
        re-admitted off a quarantined replica keeps its ORIGINAL submit
        moment, so its TTFT and deadlines honestly include the lost time."""
        if not 1 <= len(req.prompt) <= self.engine.max_len - 1:
            raise ValueError(
                f"prompt length {len(req.prompt)} must be in "
                f"[1, {self.engine.max_len - 1}]")
        if req.max_new < 1:
            raise ValueError(f"max_new={req.max_new} must be >= 1")
        rid = self._next_id
        self._next_id += 1
        rec = _Rec(rid, req, requeued=requeued,
                   submit_t=self.clock() if submit_t is None else submit_t,
                   submit_tick=self._tick,
                   trace_id=rid if trace_id is None else trace_id)
        tracer = self._tracer()
        if tracer is not None:
            rec.submit_us = tracer.now_us()
        self._recs[rid] = rec
        if requeued:
            self._requeued_in += 1
        if req.ttft_deadline_s > 0 or req.deadline_s > 0:
            self._any_deadlines = True
        if self.max_queue and len(self._queue) >= self.max_queue:
            # admission control: shed NOW with an honest hint instead of
            # joining a line that already guarantees a deadline miss
            rec.status = "shed"
            rec.retry_after_s = round(
                self.shed_retry_after_s
                * (1 + len(self._queue) / self.max_queue), 6)
            self._shed += 1
            self._remember_done(rec)
            return rid
        self._queue.append(rec)
        self._queue_peak = max(self._queue_peak, len(self._queue))
        return rid

    def poll(self, rid: int) -> dict:
        rec = self._recs[rid]
        out = {"status": rec.status, "tokens": list(rec.tokens)}
        if rec.version is not None:
            out["version"] = rec.version
        if rec.status == "shed":
            out["retry_after_s"] = rec.retry_after_s
        elif rec.status == "timeout":
            out["timeout_kind"] = rec.timeout_kind
        elif rec.status == "error":
            out["error"] = rec.error
        return out

    @property
    def pending(self) -> int:
        """Requests not yet finished (queued + prefilling + running)."""
        return (len(self._queue) + (self._admitting is not None)
                + len(self._running))

    # ------------------------------------------------------------------ tick

    def tick(self) -> None:
        """One scheduling round: deadline sweep, bounded prefill, then one
        decode step."""
        self._tick += 1
        if self._any_deadlines:
            self._sweep_deadlines()
        budget = self.prefill_chunks_per_tick or 10 ** 9
        while budget > 0:
            if self._admitting is None:
                if not (self._queue and self._free):
                    break
                rec = self._queue.popleft()
                rec.slot = self._free.pop(0)
                rec.status = "prefill"
                self._admitting = rec
                # queue time before a replica accepts — the router_wait
                # span (host clocks only: zero added device readbacks)
                if self.telemetry is not None:
                    self.telemetry.spans.add(
                        "router_wait", self.clock() - rec.submit_t)
                    tracer = self._tracer()
                    if tracer is not None:
                        tracer.complete(
                            "queue_wait", cat="request", tid=rec.trace_id,
                            t0_us=rec.submit_us, t1_us=tracer.now_us(),
                            args={"slot": rec.slot})
                # prefix-page lookup at admission (None with the cache
                # off): the pinned chain loads below, on the same budget
                pm = getattr(self.engine, "prefix_match", None)
                if pm is not None:
                    rec.handle = pm(rec.req.prompt)
            rec = self._admitting
            r = rec.req
            try:
                if rec.handle is not None and not rec.pages_loaded:
                    # the whole pinned chain lands in ONE compiled gather —
                    # n_tokens/chunk prefill chunks of work for one budget
                    # unit (it still spends budget so admission cannot
                    # starve decode, and the load deactivates the slot
                    # first)
                    self._timed("serve_page_load", self.engine.load_prefix,
                                rec.slot, rec.handle, tid=rec.trace_id)
                    rec.pages_loaded = len(rec.handle.entries)
                    budget -= 1
                    continue
                start = rec.handle.n_tokens if rec.handle is not None else 0
                # the trace id reaches the ENGINE (XPlane annotation) only
                # when it opted in — simple engines need not know about ids
                ekw = ({"trace_id": rec.trace_id}
                       if getattr(self.engine, "annotate_traces", False)
                       else {})
                out = self._timed(
                    "serve_prefill_chunk", self.engine.prefill_chunk_into,
                    rec.slot, r.prompt, rec.chunks_done, start=start,
                    temperature=r.temperature, top_k=r.top_k, top_p=r.top_p,
                    eos_id=r.eos_id, pad_id=r.pad_id, seed=r.seed,
                    tid=rec.trace_id,
                    targs={"slot": rec.slot, "chunk": rec.chunks_done},
                    **ekw)
            except Exception as e:  # noqa: BLE001 — an ADMISSION failure
                # has exactly one owner: fail that request terminally and
                # keep the replica serving (poison_request isolation).
                # Decode-path exceptions below have no single owner and
                # propagate to the Router's health machinery instead.
                self._fail(rec, e)
                budget -= 1
                continue
            rec.chunks_done += 1
            budget -= 1
            if out is not None:                      # last chunk: tok0
                tok, done = out
                save = getattr(self.engine, "save_prefix_pages", None)
                if save is not None:
                    try:
                        self._timed("serve_page_save", save, rec.slot,
                                    r.prompt, tid=rec.trace_id)
                    except Exception as e:  # noqa: BLE001 — same owner
                        self._fail(rec, e)
                        continue
                rec.first_token_t = self.clock()
                rec.first_token_tick = self._tick
                rec.tokens.append(tok)
                self._admitting = None
                self._ttfts.append(rec.first_token_t - rec.submit_t)
                self._ttft_requeued.append(rec.requeued)
                self._ttft_count += 1
                if done or self._budget_spent(rec):
                    self._finish(rec)
                else:
                    rec.status = "running"
                    self._running[rec.slot] = rec

        if self._running:
            if self.telemetry is None \
                    and not getattr(self.engine, "annotate_traces", False):
                # hottest loop, telemetry off: no per-token id-list /
                # targs allocation for data nothing would consume
                out = self.engine.decode()
            else:
                active = [r.trace_id for r in self._running.values()]
                ekw = ({"trace_ids": active}
                       if getattr(self.engine, "annotate_traces", False)
                       else {})
                out = self._timed(
                    "serve_decode", self.engine.decode,
                    targs={"trace_ids": active}, **ekw)
            now = self.clock()
            spec_k = getattr(self.engine, "spec_k", 0)
            if spec_k:
                # SPECULATIVE tick: up to k+1 tokens per slot, delivered
                # in order until the row's eos or budget — exactly the
                # sequence n_emit plain ticks would have delivered.
                toks, dones, n_emit = out
                ver = int(getattr(self.engine, "param_version", 0) or 0)
                bucket = self._accept_by_version.setdefault(ver, [0, 0])
                for slot, rec in list(self._running.items()):
                    n = int(n_emit[slot])
                    self._spec_proposed += spec_k
                    self._spec_accepted += n - 1
                    rec.proposed += spec_k
                    rec.accepted += n - 1
                    bucket[0] += spec_k
                    bucket[1] += n - 1
                    for j in range(n):
                        rec.tokens.append(int(toks[slot, j]))
                        if bool(dones[slot, j]) or self._budget_spent(rec):
                            rec.finish_t = now
                            self._finish(rec)
                            break
            else:
                toks, dones = out
                for slot, rec in list(self._running.items()):
                    rec.tokens.append(int(toks[slot]))
                    if bool(dones[slot]) or self._budget_spent(rec):
                        rec.finish_t = now
                        self._finish(rec)
        self._occupancy_sum += self._occupancy()

        if (self.writer is not None and self.log_every
                and self._tick % self.log_every == 0):
            self.writer.write_scalars(self._tick, self.stats(brief=True))

    def run_until_idle(self, max_ticks: int = 100000, *,
                       on_tick=None) -> None:
        """Drain the queue. ``on_tick`` (zero-arg, optional) fires after
        every tick — the heartbeat hook point, shared with replay()."""
        for _ in range(max_ticks):
            if not self.pending:
                return
            self.tick()
            if on_tick is not None:
                on_tick()
        raise RuntimeError(f"requests still pending after {max_ticks} ticks")

    # ------------------------------------------------------------- internals

    def _tracer(self):
        """The run's per-request TraceCollector, if one is attached to the
        telemetry object (host-clock chrome events; None = no recording)."""
        return getattr(self.telemetry, "tracer", None)

    def _timed(self, name, fn, *args, tid=None, targs=None, **kwargs):
        """Engine call under a telemetry phase span (no-op without one);
        with a TraceCollector attached, additionally one chrome event
        tagged ``tid`` (the request trace id; the shared "engine" track
        for decode steps serving many requests at once). All host
        perf_counter arithmetic — zero added device readbacks."""
        if self.telemetry is None:
            return fn(*args, **kwargs)
        tracer = self._tracer()
        if tracer is None:
            with self.telemetry.spans.span(name):
                return fn(*args, **kwargs)
        t0 = tracer.now_us()
        try:
            with self.telemetry.spans.span(name):
                return fn(*args, **kwargs)
        finally:
            tracer.complete(name, cat="engine",
                            tid="engine" if tid is None else tid,
                            t0_us=t0, t1_us=tracer.now_us(), args=targs)

    def _budget_spent(self, rec: _Rec) -> bool:
        return (len(rec.tokens) >= rec.req.max_new
                or len(rec.req.prompt) + len(rec.tokens) >= self.engine.max_len)

    def _occupancy(self) -> float:
        return 1.0 - len(self._free) / self.engine.n_slots

    # -------------------------------------------------- router admission

    @property
    def occupancy(self) -> float:
        """Occupied-slot fraction (prefilling slots included) — the
        router's primary admission signal."""
        return self._occupancy()

    @property
    def queue_depth(self) -> int:
        """Requests accepted but not yet in a slot — the router's
        admission tiebreak."""
        return len(self._queue) + (self._admitting is not None)

    @property
    def ttft_count(self) -> int:
        """Monotone TTFT-sample count (see ``_ttft_count``)."""
        return self._ttft_count

    def _finish(self, rec: _Rec) -> None:
        rec.finish_t = rec.finish_t or self.clock()
        if len(rec.tokens) > 1:
            self._tok_lats.append((rec.finish_t - rec.first_token_t)
                                  / (len(rec.tokens) - 1))
        self._completed += 1
        self._retire(rec, "done")

    def _retire(self, rec: _Rec, status: str,
                now: Optional[float] = None) -> None:
        """Shared terminal bookkeeping for done/shed/timeout/error: stamp
        the status, emit the lifecycle trace slice, release the prefix
        pin, free the slot (if the request held one) and enter the
        bounded retention window."""
        rec.status = status
        rec.finish_t = rec.finish_t or (self.clock() if now is None else now)
        if status == "done":
            # the version-stamp contract (ISSUE 14): every completed
            # record names the param version that decoded it — the
            # engine's CURRENT version is the whole request's version
            # because a swap drains in-flight work first (see _Rec)
            rec.version = getattr(self.engine, "param_version", None)
            if self._log_sink is not None:
                # the flywheel's write point (ISSUE 19): every fact here
                # is a host int/float the scheduler already holds
                self._log_sink.record({
                    "rid": rec.trace_id if rec.trace_id >= 0 else rec.rid,
                    "replica": self.replica_index,
                    "version": rec.version,
                    "status": status,
                    "prompt": [int(t) for t in rec.req.prompt],
                    "tokens": list(rec.tokens),
                    "ttft_s": round(rec.first_token_t - rec.submit_t, 6)
                    if rec.first_token_t is not None else None,
                    "latency_s": round(rec.finish_t - rec.submit_t, 6),
                    "proposed": rec.proposed,
                    "accepted": rec.accepted,
                })
        tracer = self._tracer()
        if tracer is not None:
            # the request's whole lifecycle as ONE slice on its own track
            # — renders submit → terminal in Perfetto with the engine-call
            # slices (tagged with the same trace id) nested visually
            args = {"rid": rec.rid, "status": status,
                    "prompt_len": len(rec.req.prompt),
                    "tokens": len(rec.tokens)}
            if rec.first_token_t is not None:
                args["ttft_s"] = round(rec.first_token_t - rec.submit_t, 6)
            tracer.complete("request", cat="request", tid=rec.trace_id,
                            t0_us=rec.submit_us, t1_us=tracer.now_us(),
                            args=args)
        if rec.handle is not None:       # refcount release on slot evict
            self.engine.release_prefix(rec.handle)
            rec.handle = None
        if rec.slot >= 0:
            self._running.pop(rec.slot, None)
            self._free.append(rec.slot)
            self._free.sort()
            rec.slot = -1
        self._remember_done(rec)

    def _remember_done(self, rec: _Rec) -> None:
        self._done_order.append(rec.rid)
        while len(self._done_order) > self.completed_cap:
            self._recs.pop(self._done_order.popleft(), None)

    def _fail(self, rec: _Rec, e: BaseException) -> None:
        """An admission-path engine failure owned by ``rec``: fail it
        terminally (status ``error``) and keep serving — the chaos
        contract that one poisoned request cannot take the replica with
        it. The device slot needs no cleanup: a half-prefilled slot is
        stale state the next admission fully resets (PR 4 contract)."""
        self._request_errors += 1
        rec.error = repr(e)[:200]
        log.warning("request %d failed in admission: %s",
                    rec.rid, rec.error)
        if self._admitting is rec:
            self._admitting = None
        self._retire(rec, "error")

    def _timeout(self, rec: _Rec, kind: str, now: float) -> None:
        self._timeouts += 1
        if kind == "ttft":
            self._timeouts_ttft += 1
        rec.timeout_kind = kind
        self._retire(rec, "timeout", now)

    def _deadline_kind(self, rec: _Rec, now: float) -> Optional[str]:
        r = rec.req
        waited = now - rec.submit_t
        if (r.ttft_deadline_s > 0 and rec.first_token_t is None
                and waited >= r.ttft_deadline_s):
            return "ttft"
        if r.deadline_s > 0 and waited >= r.deadline_s:
            return "total"
        return None

    def _sweep_deadlines(self) -> None:
        """Evict every request past its deadline — queued, mid-prefill or
        decoding alike (the freed slot is reusable this same tick). An
        abandoned mid-prefill slot leaves only stale device state the
        next admission resets."""
        now = self.clock()
        for rec in [rec for rec in self._queue
                    if self._deadline_kind(rec, now)]:
            self._queue.remove(rec)
            self._timeout(rec, self._deadline_kind(rec, now), now)
        rec = self._admitting
        if rec is not None:
            kind = self._deadline_kind(rec, now)
            if kind:
                self._admitting = None
                self._timeout(rec, kind, now)
        for rec in list(self._running.values()):
            kind = self._deadline_kind(rec, now)
            if kind:
                self._timeout(rec, kind, now)

    # ------------------------------------------------------ quarantine drain

    def evict_for_requeue(self) -> list:
        """Vacate every in-flight request (queued + admitting + running)
        for re-admission elsewhere — the Router's quarantine drain. The
        records are returned in SUBMIT order (deterministic re-routing),
        marked ``requeued`` here as tombstones; their prefix pins are
        released (host-side index work — safe against a wedged engine),
        tokens are cleared (survivors regenerate the full deterministic
        stream), and every slot is freed. The engine's device state needs
        no touch: stale slots are masked spectators until re-admission
        resets them."""
        recs = list(self._queue)
        if self._admitting is not None:
            recs.append(self._admitting)
        recs += list(self._running.values())
        recs.sort(key=lambda r: r.rid)
        self._queue.clear()
        self._admitting = None
        self._running.clear()
        self._free = list(range(self.engine.n_slots))
        for rec in recs:
            if rec.handle is not None:
                try:
                    self.engine.release_prefix(rec.handle)
                except Exception:  # noqa: BLE001 — draining a broken
                    pass           # replica must not fail the requeue
                rec.handle = None
            rec.pages_loaded = 0
            rec.slot = -1
            rec.tokens = []
            rec.proposed = 0
            rec.accepted = 0
            rec.status = "requeued"
            self._requeued_out += 1
        return recs

    def release(self, rid: int) -> None:
        """Drop a completed request's record (tokens included) — call after
        consuming the result to keep a long-running server's host memory
        flat without relying on the completed_cap backstop."""
        rec = self._recs.get(rid)
        if rec is not None and rec.status == "done":
            self._recs.pop(rid, None)

    # ----------------------------------------------------------- postmortem

    def postmortem_state(self) -> dict:
        """In-flight request ids + per-slot ages for the flight-recorder
        dump — pure host clocks and counters (the dump fires exactly when
        the backend may be wedged, so NO device API on this path)."""
        now = self.clock()
        in_flight, slot_ages = [], {}
        recs = list(self._queue)
        if self._admitting is not None:
            recs.append(self._admitting)
        recs += list(self._running.values())
        for rec in recs:
            in_flight.append({
                "rid": rec.rid, "trace_id": rec.trace_id,
                "status": rec.status, "slot": rec.slot,
                "age_s": round(now - rec.submit_t, 3),
                "tokens": len(rec.tokens)})
            if rec.slot >= 0:
                slot_ages[str(rec.slot)] = round(now - rec.submit_t, 3)
        return {"in_flight": in_flight,
                "queue_depth": len(self._queue),
                "occupancy": round(self._occupancy(), 4),
                "slot_ages_s": slot_ages,
                "completed": self._completed,
                "shed": self._shed,
                "timeouts": self._timeouts,
                "request_errors": self._request_errors,
                "requeued_out": self._requeued_out,
                "requeued_in": self._requeued_in}

    # --------------------------------------------------------------- metrics

    def accept_by_version(self) -> dict:
        """Per-param-version speculative acceptance counts,
        ``{version: (proposed, accepted)}`` — raw ints so a Router can
        fleet-sum them (the rate panel lives in :meth:`stats`)."""
        return {v: (b[0], b[1])
                for v, b in sorted(self._accept_by_version.items())}

    def stats(self, brief: bool = False) -> dict:
        """Aggregate serving metrics (floats, MetricWriter-compatible)."""
        out = {
            "serve_queue_depth": float(len(self._queue)
                                       + (self._admitting is not None)),
            "serve_occupancy": self._occupancy(),
            "serve_completed": float(self._completed),
        }
        if brief:
            if self._ttfts:
                out["serve_ttft_last_s"] = self._ttfts[-1]
            return out
        out.update({
            "serve_ticks": float(self._tick),
            "serve_shed": float(self._shed),
            "serve_timeouts": float(self._timeouts),
            "serve_timeouts_ttft": float(self._timeouts_ttft),
            "serve_request_errors": float(self._request_errors),
            "serve_requeued_out": float(self._requeued_out),
            "serve_requeued_in": float(self._requeued_in),
            "serve_queue_peak": float(self._queue_peak),
            "serve_occupancy_mean": (self._occupancy_sum / self._tick
                                     if self._tick else 0.0),
            "serve_ttft_p50_s": _quantile(self._ttfts, 0.5),
            "serve_ttft_p99_s": _quantile(self._ttfts, 0.99),
            "serve_tok_latency_p50_s": _quantile(self._tok_lats, 0.5),
            "serve_tok_latency_p99_s": _quantile(self._tok_lats, 0.99),
        })
        if self._spec_proposed:
            out["serve_spec_accept_rate"] = (self._spec_accepted
                                             / self._spec_proposed)
        for v, (prop, acc) in sorted(self._accept_by_version.items()):
            if prop:
                out[f"serve_spec_accept_rate_v{v}"] = acc / prop
        if self.ttft_slo_s > 0.0:
            out["serve_ttft_slo_ok_frac"] = (
                sum(1 for t in self._ttfts if t <= self.ttft_slo_s)
                / len(self._ttfts) if self._ttfts else 1.0)
        counters = getattr(self.engine, "counters", None)
        if counters is not None:
            out.update({f"serve_{k}": float(v) for k, v in counters.items()})
        prefix = getattr(self.engine, "prefix_stats", None)
        if prefix is not None:
            out.update({f"serve_prefix_{k}": float(v)
                        for k, v in prefix().items()})
        if self.telemetry is not None:
            for name, roll in self.telemetry.spans.rollup().items():
                if name.startswith("serve_") or name == "router_wait":
                    out[f"{name}_p50_s"] = roll["p50_s"]
                    out[f"{name}_p99_s"] = roll["p99_s"]
        return out
