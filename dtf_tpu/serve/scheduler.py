"""Request scheduling over a :class:`~dtf_tpu.serve.engine.DecodeEngine`.

FIFO admission with prefill/decode interleave: each :meth:`Scheduler.tick`
runs at most ``prefill_chunks_per_tick`` prompt chunks (admitting queued
requests into free slots as chunk budget allows — a long prompt spreads its
prefill over several ticks instead of stalling everyone's decode), then one
``decode_all`` step for every occupied slot. Slots are evicted on EOS, on
``max_new``, or when the slot's ``max_len`` budget fills; the freed slot is
immediately reusable next tick — the continuous-batching loop.

Observability rides :class:`dtf_tpu.metrics.MetricWriter` (the training
stack's writer): queue depth and slot occupancy per logging interval, plus
per-request TTFT and per-token latency on completion. ``stats()`` returns
the same aggregates for benches (``scripts/serve_gpt.py`` prints them as
its one JSON line). With a :class:`dtf_tpu.telemetry.Telemetry` attached
the engine calls are additionally recorded as ``serve_prefill_chunk`` /
``serve_decode`` phase spans (host wall time per compiled-program call —
the training loop's data_wait/dispatch decomposition, serving edition) and
``stats()`` gains their p50/p99.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Optional, Sequence

from dtf_tpu.metrics import quantile as _quantile


@dataclasses.dataclass(frozen=True)
class Request:
    """One decode request. Sampling fields mirror ``gpt.generate``."""

    prompt: Sequence[int]
    max_new: int = 32
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_id: Optional[int] = None
    pad_id: int = 0
    seed: int = 0


@dataclasses.dataclass
class _Rec:
    rid: int
    req: Request
    status: str = "queued"            # queued | prefill | running | done
    slot: int = -1
    chunks_done: int = 0
    tokens: list = dataclasses.field(default_factory=list)
    submit_t: float = 0.0
    first_token_t: float = 0.0
    finish_t: float = 0.0


class Scheduler:
    """FIFO continuous-batching scheduler (see module docstring).

    ``prefill_chunks_per_tick`` bounds how much prefill work may delay the
    next decode step (0 = admit greedily, whole queue's worth per tick).
    ``clock`` is injectable for deterministic tests.
    """

    def __init__(self, engine, writer=None, *, log_every: int = 0,
                 prefill_chunks_per_tick: int = 4, clock=time.monotonic,
                 completed_cap: int = 100_000, telemetry=None):
        self.engine = engine
        self.writer = writer
        self.log_every = log_every
        self.telemetry = telemetry
        if prefill_chunks_per_tick < 0:
            # a negative budget would be truthy in tick()'s `or 10**9`
            # fallback yet fail `> 0` — admission silently off, replay()
            # spinning forever on a non-empty queue
            raise ValueError(
                f"prefill_chunks_per_tick={prefill_chunks_per_tick} must "
                "be >= 0 (0 = admit greedily)")
        self.prefill_chunks_per_tick = prefill_chunks_per_tick
        self.clock = clock
        #: completed records (and latency samples) retained for poll();
        #: beyond the cap the OLDEST finished request is forgotten — a
        #: long-running server must not grow host memory per request.
        #: poll() of a forgotten id raises KeyError; callers that need a
        #: result must collect it within cap completions (or raise the cap).
        self.completed_cap = completed_cap
        self._free = list(range(engine.n_slots))
        self._queue: collections.deque[_Rec] = collections.deque()
        self._admitting: Optional[_Rec] = None
        self._running: dict[int, _Rec] = {}
        self._recs: dict[int, _Rec] = {}
        self._done_order: collections.deque[int] = collections.deque()
        self._next_id = 0
        self._tick = 0
        self._ttfts: collections.deque[float] = collections.deque(
            maxlen=completed_cap)
        self._tok_lats: collections.deque[float] = collections.deque(
            maxlen=completed_cap)
        self._completed = 0
        self._occupancy_sum = 0.0
        self._queue_peak = 0

    # ----------------------------------------------------------- submit/poll

    def submit(self, req: Request) -> int:
        if not 1 <= len(req.prompt) <= self.engine.max_len - 1:
            raise ValueError(
                f"prompt length {len(req.prompt)} must be in "
                f"[1, {self.engine.max_len - 1}]")
        if req.max_new < 1:
            raise ValueError(f"max_new={req.max_new} must be >= 1")
        rid = self._next_id
        self._next_id += 1
        rec = _Rec(rid, req, submit_t=self.clock())
        self._recs[rid] = rec
        self._queue.append(rec)
        self._queue_peak = max(self._queue_peak, len(self._queue))
        return rid

    def poll(self, rid: int) -> dict:
        rec = self._recs[rid]
        return {"status": rec.status, "tokens": list(rec.tokens)}

    @property
    def pending(self) -> int:
        """Requests not yet finished (queued + prefilling + running)."""
        return (len(self._queue) + (self._admitting is not None)
                + len(self._running))

    # ------------------------------------------------------------------ tick

    def tick(self) -> None:
        """One scheduling round: bounded prefill, then one decode step."""
        self._tick += 1
        budget = self.prefill_chunks_per_tick or 10 ** 9
        while budget > 0:
            if self._admitting is None:
                if not (self._queue and self._free):
                    break
                rec = self._queue.popleft()
                rec.slot = self._free.pop(0)
                rec.status = "prefill"
                self._admitting = rec
            rec = self._admitting
            r = rec.req
            out = self._timed(
                "serve_prefill_chunk", self.engine.prefill_chunk_into,
                rec.slot, r.prompt, rec.chunks_done,
                temperature=r.temperature, top_k=r.top_k, top_p=r.top_p,
                eos_id=r.eos_id, pad_id=r.pad_id, seed=r.seed)
            rec.chunks_done += 1
            budget -= 1
            if out is not None:                      # last chunk: tok0
                tok, done = out
                rec.first_token_t = self.clock()
                rec.tokens.append(tok)
                self._admitting = None
                self._ttfts.append(rec.first_token_t - rec.submit_t)
                if done or self._budget_spent(rec):
                    self._finish(rec)
                else:
                    rec.status = "running"
                    self._running[rec.slot] = rec

        if self._running:
            toks, dones = self._timed("serve_decode", self.engine.decode)
            now = self.clock()
            for slot, rec in list(self._running.items()):
                rec.tokens.append(int(toks[slot]))
                if bool(dones[slot]) or self._budget_spent(rec):
                    rec.finish_t = now
                    self._finish(rec)
        self._occupancy_sum += self._occupancy()

        if (self.writer is not None and self.log_every
                and self._tick % self.log_every == 0):
            self.writer.write_scalars(self._tick, self.stats(brief=True))

    def run_until_idle(self, max_ticks: int = 100000) -> None:
        for _ in range(max_ticks):
            if not self.pending:
                return
            self.tick()
        raise RuntimeError(f"requests still pending after {max_ticks} ticks")

    # ------------------------------------------------------------- internals

    def _timed(self, name, fn, *args, **kwargs):
        """Engine call under a telemetry phase span (no-op without one)."""
        if self.telemetry is None:
            return fn(*args, **kwargs)
        with self.telemetry.spans.span(name):
            return fn(*args, **kwargs)

    def _budget_spent(self, rec: _Rec) -> bool:
        return (len(rec.tokens) >= rec.req.max_new
                or len(rec.req.prompt) + len(rec.tokens) >= self.engine.max_len)

    def _occupancy(self) -> float:
        return 1.0 - len(self._free) / self.engine.n_slots

    def _finish(self, rec: _Rec) -> None:
        rec.status = "done"
        rec.finish_t = rec.finish_t or self.clock()
        if len(rec.tokens) > 1:
            self._tok_lats.append((rec.finish_t - rec.first_token_t)
                                  / (len(rec.tokens) - 1))
        self._completed += 1
        self._running.pop(rec.slot, None)
        self._free.append(rec.slot)
        self._free.sort()
        rec.slot = -1
        self._done_order.append(rec.rid)
        while len(self._done_order) > self.completed_cap:
            self._recs.pop(self._done_order.popleft(), None)

    def release(self, rid: int) -> None:
        """Drop a completed request's record (tokens included) — call after
        consuming the result to keep a long-running server's host memory
        flat without relying on the completed_cap backstop."""
        rec = self._recs.get(rid)
        if rec is not None and rec.status == "done":
            self._recs.pop(rid, None)

    # --------------------------------------------------------------- metrics

    def stats(self, brief: bool = False) -> dict:
        """Aggregate serving metrics (floats, MetricWriter-compatible)."""
        out = {
            "serve_queue_depth": float(len(self._queue)
                                       + (self._admitting is not None)),
            "serve_occupancy": self._occupancy(),
            "serve_completed": float(self._completed),
        }
        if brief:
            if self._ttfts:
                out["serve_ttft_last_s"] = self._ttfts[-1]
            return out
        out.update({
            "serve_ticks": float(self._tick),
            "serve_queue_peak": float(self._queue_peak),
            "serve_occupancy_mean": (self._occupancy_sum / self._tick
                                     if self._tick else 0.0),
            "serve_ttft_p50_s": _quantile(self._ttfts, 0.5),
            "serve_ttft_p99_s": _quantile(self._ttfts, 0.99),
            "serve_tok_latency_p50_s": _quantile(self._tok_lats, 0.5),
            "serve_tok_latency_p99_s": _quantile(self._tok_lats, 0.99),
        })
        if self.telemetry is not None:
            for name, roll in self.telemetry.spans.rollup().items():
                if name.startswith("serve_"):
                    out[f"{name}_p50_s"] = roll["p50_s"]
                    out[f"{name}_p99_s"] = roll["p99_s"]
        return out
