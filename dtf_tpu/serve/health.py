"""Replica health — quarantine, probation, and serve-side fault injection.

PR 11 made *training* survive a wedged or lost host; this module is the
same discipline for the PR 6 serve fleet. One wedged replica must not
stall the Router's pump loop, and "the fleet never stops serving" has to
be a tested property, so every replica carries a tiny state machine:

    healthy ──slow ticks──▶ degraded ──more/worse──▶ quarantined
       ▲                        │                         │
       └──── clean tick ────────┘      probation delay    │
       ▲                                                  ▼
       └──── N clean ticks/probes ◀──────────────── probation

- **healthy / degraded** — routable. Degraded replicas (one or more slow
  ticks) lose admission priority but keep serving.
- **quarantined** — NOT routable: the Router's ``_pick`` skips it, its
  in-flight requests are requeued onto survivors, and its ticks stop, so
  a wedged engine is never called again and the pump loop stays fast.
- **probation** — after ``probation_delay_s`` a quarantined replica is
  re-admitted on trial (lowest routing priority; an idle probation
  replica is exercised via ``DecodeEngine.probe`` instead of waiting for
  traffic). ``probation_ticks`` clean ticks promote it back to healthy;
  one slow tick or fault re-quarantines with the delay doubled
  (exponential backoff, capped — the run-controller relaunch idiom).

Slow is the PR 11 stall bar: a tick is slow when its wall time exceeds
``max(min_slow_s, slow_factor × p99 of recent HEALTHY ticks)`` — the p99
baseline deliberately excludes slow ticks so a wedge cannot raise its own
bar. A single tick past ``wedge_s`` skips degraded and quarantines
outright. All host clock arithmetic (injectable ``clock`` for
deterministic tests); zero device readbacks, and the tracker never calls
into an engine itself — a wedged backend cannot hang its own watchdog.

The bottom half is the serve edition of :mod:`dtf_tpu.fault.inject`:
:func:`install_serve_fault` arms a ``DTF_FAULT_INJECT`` serve verb
(``wedge_replica@tick:replica=k`` / ``slow_decode@tick`` /
``poison_request@n``) on a live Router/Scheduler by wrapping engine
methods — the chaos tests and the degraded-fleet bench row drive the REAL
pump through it, the way PR 11's verbs ride the real trainers.

jax-free at module level (the telemetry/tune/fault convention): health is
pure host bookkeeping. docs/RESILIENCE.md walks the serving section.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import logging
import os
import time
from typing import Optional

from dtf_tpu.fault.inject import (InjectedCrash, InjectedPoison,
                                  ServeFaultPlan, corrupt_publish_version)
from dtf_tpu.metrics import quantile

log = logging.getLogger("dtf_tpu")

HEALTHY = "healthy"
DEGRADED = "degraded"
QUARANTINED = "quarantined"
PROBATION = "probation"

#: routing priority per state (``Router._pick`` sorts on this first);
#: quarantined is absent on purpose — it is never a candidate.
_RANK = {HEALTHY: 0, DEGRADED: 1, PROBATION: 2}


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """Thresholds of the replica state machine (module docstring).

    Defaults are deliberately conservative for the CPU sim: a legitimate
    prefill-heavy tick on a sim replica can run hundreds of ms, while a
    real wedge is *forever* — ``min_slow_s`` only needs to sit well under
    the caller's patience, not near the median tick.
    """

    slow_factor: float = 20.0      # × p99 of recent healthy ticks
    min_slow_s: float = 5.0        # floor under the adaptive bar
    wedge_s: float = 20.0          # one tick this slow → quarantine now
    degrade_after: int = 1         # consecutive slow ticks → degraded
    quarantine_after: int = 3      # consecutive slow ticks → quarantined
    probation_delay_s: float = 10.0
    probation_backoff: float = 2.0   # delay multiplier per failed probation
    probation_delay_max_s: float = 300.0
    probation_ticks: int = 3       # clean ticks/probes to re-admit fully
    keep: int = 64                 # healthy-tick baseline window

    def __post_init__(self):
        if not 1 <= self.degrade_after <= self.quarantine_after:
            raise ValueError(
                f"need 1 <= degrade_after ({self.degrade_after}) <= "
                f"quarantine_after ({self.quarantine_after})")
        if self.probation_ticks < 1:
            raise ValueError(
                f"probation_ticks={self.probation_ticks} must be >= 1")
        if self.min_slow_s <= 0 or self.wedge_s < self.min_slow_s:
            raise ValueError(
                f"need 0 < min_slow_s ({self.min_slow_s}) <= wedge_s "
                f"({self.wedge_s})")
        if self.probation_backoff < 1.0:
            raise ValueError(
                f"probation_backoff={self.probation_backoff} must be >= 1 "
                "(a shrinking delay would hammer a dead replica)")


@dataclasses.dataclass
class _Replica:
    state: str = HEALTHY
    strikes: int = 0               # consecutive slow ticks
    ok_probation: int = 0          # clean ticks inside this probation
    since: float = 0.0             # clock() of the last transition
    delay_s: float = 0.0           # current quarantine→probation delay
    last_cause: str = ""
    durations: collections.deque = dataclasses.field(
        default_factory=collections.deque)


class HealthTracker:
    """Per-replica state machines + fleet counters (module docstring).

    The Router owns one and feeds it ``note_tick(i, wall_s)`` after every
    replica tick and ``note_fault(i, err)`` on an engine exception; it
    reads back ``routable``/``rank`` for admission and ``state``/
    ``counters`` for stats and postmortems.
    """

    def __init__(self, n_replicas: int, cfg: Optional[HealthConfig] = None,
                 *, clock=time.monotonic, events=None):
        if n_replicas < 1:
            raise ValueError(f"n_replicas={n_replicas} must be >= 1")
        self.cfg = cfg or HealthConfig()
        self.clock = clock
        #: optional fleet EventLog (dtf_tpu/telemetry/events.py) — every
        #: transition verdict lands on the run timeline too.
        self.events = events
        self._r = [
            _Replica(delay_s=self.cfg.probation_delay_s,
                     durations=collections.deque(maxlen=self.cfg.keep))
            for _ in range(n_replicas)]
        self.counters = {"quarantines": 0, "slow_ticks": 0, "faults": 0,
                         "probations": 0, "readmits": 0}
        #: bounded transition log (newest last) — the serve postmortem
        #: names every verdict with its cause, controller-style.
        self.transitions: collections.deque = collections.deque(maxlen=100)

    # ------------------------------------------------------------- verdicts

    def threshold_s(self, i: int) -> float:
        """The slow bar for replica ``i`` — the PR 11 stall idiom over the
        replica's recent HEALTHY tick durations."""
        slow = quantile(list(self._r[i].durations), 0.99)
        return max(self.cfg.min_slow_s,
                   self.cfg.slow_factor * slow if slow is not None else 0.0)

    def note_tick(self, i: int, dur_s: float) -> Optional[str]:
        """One completed replica tick of ``dur_s`` wall seconds. Returns
        the new state on a transition (the Router requeues on
        ``QUARANTINED``), None when nothing changed."""
        h = self._r[i]
        cfg = self.cfg
        thresh = self.threshold_s(i)
        if dur_s < thresh:
            h.durations.append(dur_s)
            h.strikes = 0
            if h.state == PROBATION:
                h.ok_probation += 1
                if h.ok_probation >= cfg.probation_ticks:
                    self.counters["readmits"] += 1
                    h.delay_s = cfg.probation_delay_s       # reset backoff
                    return self._transit(
                        i, HEALTHY,
                        f"probation passed ({cfg.probation_ticks} clean)")
            elif h.state == DEGRADED:
                return self._transit(i, HEALTHY, "recovered")
            return None
        self.counters["slow_ticks"] += 1
        h.strikes += 1
        if dur_s >= cfg.wedge_s:
            cause = f"tick {dur_s:.3f}s >= wedge bar {cfg.wedge_s:.3f}s"
        else:
            cause = (f"tick {dur_s:.3f}s >= threshold {thresh:.3f}s "
                     f"(strike {h.strikes})")
        if (h.state == PROBATION or dur_s >= cfg.wedge_s
                or h.strikes >= cfg.quarantine_after):
            return self._quarantine(i, cause)
        if h.strikes >= cfg.degrade_after and h.state == HEALTHY:
            return self._transit(i, DEGRADED, cause)
        return None

    def note_fault(self, i: int, err: BaseException) -> str:
        """An engine exception with no single owning request (the decode
        path) — quarantine on the spot."""
        self.counters["faults"] += 1
        if self._r[i].state == QUARANTINED:
            return QUARANTINED
        return self._quarantine(i, f"engine fault: {repr(err)[:120]}")

    def quarantine(self, i: int, cause: str) -> str:
        """Forced quarantine (operator/test API — the Router's
        :meth:`~dtf_tpu.serve.router.Router.quarantine` rides this)."""
        if self._r[i].state == QUARANTINED:
            return QUARANTINED
        return self._quarantine(i, cause)

    def _quarantine(self, i: int, cause: str) -> str:
        h = self._r[i]
        if h.state == PROBATION:
            # a failed probation doubles the next wait — the controller's
            # relaunch backoff, serving edition
            h.delay_s = min(h.delay_s * self.cfg.probation_backoff,
                            self.cfg.probation_delay_max_s)
        h.strikes = 0
        h.ok_probation = 0
        self.counters["quarantines"] += 1
        return self._transit(i, QUARANTINED, cause)

    def _transit(self, i: int, state: str, cause: str) -> str:
        h = self._r[i]
        old, h.state = h.state, state
        h.since = self.clock()
        h.last_cause = cause
        self.transitions.append({"replica": i, "from": old, "to": state,
                                 "cause": cause, "t": round(h.since, 3)})
        if self.events is not None:
            # "at" = the tracker's own (injectable) clock: episode
            # durations on the timeline are deltas in THIS domain, while
            # the sink's wall "t" keeps the record ordered against the
            # other subsystems' events
            self.events.emit("health_transition", replica=i, state_from=old,
                             state_to=state, cause=cause,
                             at=round(h.since, 6))
        log.warning("serve replica %d: %s -> %s (%s)", i, old, state, cause)
        return state

    # ------------------------------------------------------------- admission

    def routable(self, i: int) -> bool:
        """May the Router send replica ``i`` work / tick it? Flips a
        quarantined replica whose delay elapsed into PROBATION lazily —
        the tracker needs no thread of its own."""
        h = self._r[i]
        if h.state != QUARANTINED:
            return True
        if self.clock() - h.since >= h.delay_s:
            h.ok_probation = 0
            self.counters["probations"] += 1
            self._transit(i, PROBATION,
                          f"probation after {h.delay_s:.1f}s quarantine")
            return True
        return False

    def rank(self, i: int) -> int:
        """Routing priority (0 best) — degraded after healthy, probation
        last, so trial traffic only lands when the fleet has no better
        home for it."""
        return _RANK.get(self._r[i].state, 3)

    def state(self, i: int) -> str:
        return self._r[i].state

    def states(self) -> list[str]:
        return [h.state for h in self._r]

    def quarantined_eta_s(self) -> Optional[float]:
        """Seconds until the NEXT quarantined replica reaches probation —
        the honest retry-after hint for a fully-quarantined fleet. None
        when nothing is quarantined."""
        now = self.clock()
        etas = [max(0.0, h.delay_s - (now - h.since))
                for h in self._r if h.state == QUARANTINED]
        return min(etas) if etas else None


# ---------------------------------------------------------------------------
# Serve-side fault injection (the chaos half).
# ---------------------------------------------------------------------------

class ServeFaultState:
    """What an installed plan has done so far (tests assert on it)."""

    def __init__(self, plan: ServeFaultPlan):
        self.plan = plan
        self.fired = False
        self.poison_prompt: Optional[tuple] = None


def install_serve_fault(plan: ServeFaultPlan, pump, *, sleep=time.sleep,
                        wedge_s: Optional[float] = None,
                        slow_s: Optional[float] = None,
                        watcher=None, emit=None) -> ServeFaultState:
    """Arm a serve fault on a live Router or Scheduler (``pump``).

    - ``wedge_replica@N[:replica=k]`` — from the target engine's N-th
      decode call on, every decode sleeps ``wedge_s`` (env
      ``DTF_FAULT_WEDGE_S``, default 0.75): alive but useless, exactly the
      signature the health watchdog must quarantine on.
    - ``slow_decode@N[:replica=k]`` — same shape, shorter ``slow_s``
      sleeps (env ``DTF_FAULT_SLOW_S``, default 0.2): degrades without
      wedging, the tail-latency chaos case.
    - ``poison_request@N`` — the N-th ``submit`` (0-based) is marked; any
      prefill chunk of that request raises :class:`InjectedPoison`
      wherever it lands, even after a requeue. The scheduler must isolate
      it (terminal ``error`` status) without taking the replica down.
    - ``poison_draft@N`` — the N-th submit is marked; while that request
      is RUNNING on a replica, the replica's ``draft_propose`` raises
      :class:`InjectedPoison`. The engine must fall back to plain decode
      (verify with null proposals — ``draft_fallbacks`` counts) instead
      of erroring the request or the replica: speculation is an
      optimization, never a correctness dependency.
    - ``wedge_in_swap@N[:replica=k]`` — the targeted replica's N-th
      ``swap_params`` call (0-based) sleeps ``wedge_s`` then raises
      mid-rolling-swap. The Router must roll the partial fleet back onto
      ONE version (docs/RESILIENCE.md §9); fires once.
    - ``corrupt_publish@N`` — needs ``watcher`` (a
      :class:`dtf_tpu.publish.PublishWatcher`): the N-th NEW published
      version the watcher observes (0-based) is damaged on disk before
      it loads. The digest check must skip it with a WARN and the fleet
      keeps serving its current version.
    - ``corrupt_log_record@N`` — the serve-log sink's N-th record written
      (0-based) gets a damaged CRC: a mounting
      :class:`~dtf_tpu.data.stream.servelog.ServeLogSource` must skip it
      with one WARN, exactly the bit-rot branch. No-op without a sink.
    - ``crash_in_log_rotate@N`` — the sink's N-th rotation (0-based)
      raises after the shard is durable but BEFORE its manifest commit:
      the next sink over the directory must ADOPT the orphan shard —
      committed records are never lost. No-op without a sink.
    - ``crash_in_event_rotate@N`` — the same crash seam on the pump's
      fleet :class:`~dtf_tpu.telemetry.events.EventLog` (``pump.events``):
      the next event log over the directory must adopt the orphan event
      shard and the timeline must still close every episode. No-op
      without an event log.

    Ticks are counted in the TARGET's own call domain (decode calls /
    submits) so plans stay deterministic under Poisson timing. ``sleep``
    is injectable — fast tests pass a fake clock's ``advance``. Each
    firing prints one JSON line first (the FaultHook contract: a failed
    recovery assertion must still show where the fault landed).
    """
    scheds = getattr(pump, "schedulers", None) or [pump]
    state = ServeFaultState(plan)
    _emit = emit or (lambda line: print(line, flush=True))

    def note(what: str, **kw) -> None:
        try:
            _emit(json.dumps({
                "fault_inject": what, "kind": plan.kind, "tick": plan.tick,
                "replica": plan.replica, "pid": os.getpid(), **kw}))
        except Exception:   # noqa: BLE001 — reporting must not alter the
            pass            # scenario under test

    if plan.kind == "poison_request":
        orig_submit = pump.submit
        count = [0]

        def submit(req, **kw):
            if count[0] == plan.tick and state.poison_prompt is None:
                state.poison_prompt = tuple(int(t) for t in req.prompt)
                note("poison_armed", submit_index=count[0])
            count[0] += 1
            return orig_submit(req, **kw)

        pump.submit = submit
        for s in scheds:
            eng = s.engine
            orig = eng.prefill_chunk_into

            def prefill(slot, prompt, chunk_i, *, _orig=orig, **kw):
                if (state.poison_prompt is not None
                        and tuple(int(t) for t in prompt)
                        == state.poison_prompt):
                    if not state.fired:
                        state.fired = True
                        note("firing")
                    raise InjectedPoison(
                        f"injected poison request (submit #{plan.tick})")
                return _orig(slot, prompt, chunk_i, **kw)

            eng.prefill_chunk_into = prefill
        return state

    if plan.kind == "poison_draft":
        orig_submit = pump.submit
        count = [0]

        def submit(req, **kw):
            if count[0] == plan.tick and state.poison_prompt is None:
                state.poison_prompt = tuple(int(t) for t in req.prompt)
                note("poison_armed", submit_index=count[0])
            count[0] += 1
            return orig_submit(req, **kw)

        pump.submit = submit
        for s in scheds:
            eng = s.engine
            if not getattr(eng, "spec_k", 0):
                continue            # non-speculative engine (fakes, or a
                                    # disagg prefill replica): no draft
                                    # runs there — the plan no-ops
            orig = eng.draft_propose

            def draft(*, _orig=orig, _s=s, **kw):
                if state.poison_prompt is not None and any(
                        tuple(int(t) for t in r.req.prompt)
                        == state.poison_prompt
                        for r in _s._running.values()):
                    if not state.fired:
                        state.fired = True
                        note("firing")
                    raise InjectedPoison(
                        f"injected draft poison (submit #{plan.tick})")
                return _orig(**kw)

            eng.draft_propose = draft
        return state

    if plan.kind == "wedge_in_swap":
        delay = (wedge_s if wedge_s is not None
                 else float(os.environ.get("DTF_FAULT_WEDGE_S", "0.75")))
        for k, s in enumerate(scheds):
            if plan.replica is not None and plan.replica != k:
                continue
            eng = s.engine
            orig = getattr(eng, "swap_params", None)
            if orig is None:
                continue        # fakes without a swap surface: no-op
            calls = [0]

            def swap(*a, _orig=orig, _calls=calls, _k=k, **kw):
                idx = _calls[0]
                _calls[0] += 1
                if idx == plan.tick and not state.fired:
                    state.fired = True
                    note("firing", on_replica=_k, delay_s=delay)
                    sleep(delay)
                    raise InjectedCrash(
                        f"injected wedge_in_swap on replica {_k} "
                        f"(swap call #{idx})")
                return _orig(*a, **kw)

            eng.swap_params = swap
        return state

    if plan.kind == "corrupt_publish":
        if watcher is None:
            return state        # nothing to arm without a publish watcher
        orig_load = watcher.load_new
        seen: list = []

        def load_new(*, _orig=orig_load):
            m = watcher.poll()
            if m is not None:
                v = int(m["version"])
                if v not in seen:
                    seen.append(v)
                    if len(seen) - 1 == plan.tick and not state.fired:
                        state.fired = True
                        note("firing", version=v)
                        try:
                            corrupt_publish_version(watcher.directory, v)
                        except FileNotFoundError:
                            pass   # raced a prune; nothing to corrupt
            return _orig()

        watcher.load_new = load_new
        return state

    if plan.kind in ("corrupt_log_record", "crash_in_log_rotate"):
        # the serve-log sink seams (ISSUE 19): a Router's replicas SHARE
        # one sink — arm each DISTINCT sink once, counting in its own
        # record/rotation domain (deterministic under Poisson timing)
        seen_sinks: set = set()
        for s in scheds:
            sink = getattr(s, "_log_sink", None)
            if sink is None or id(sink) in seen_sinks:
                continue
            seen_sinks.add(id(sink))

            def mark(what: str) -> None:
                state.fired = True
                note(what)

            if plan.kind == "corrupt_log_record":
                sink.arm_corrupt(plan.tick, note=mark)
            else:
                sink.arm_crash_rotate(plan.tick, note=mark)
        return state

    if plan.kind == "crash_in_event_rotate":
        # the fleet event log's crash seam (ISSUE 20) — same shape as the
        # sink verbs, armed on the pump-shared EventLog
        events = getattr(pump, "events", None)
        if events is not None:
            def mark_ev(what: str) -> None:
                state.fired = True
                note(what)

            events.arm_crash_rotate(plan.tick, note=mark_ev)
        return state

    delay = (wedge_s if wedge_s is not None
             else float(os.environ.get("DTF_FAULT_WEDGE_S", "0.75"))) \
        if plan.kind == "wedge_replica" else \
        (slow_s if slow_s is not None
         else float(os.environ.get("DTF_FAULT_SLOW_S", "0.2")))
    for k, s in enumerate(scheds):
        if plan.replica is not None and plan.replica != k:
            continue
        eng = s.engine
        orig = eng.decode
        calls = [0]

        def decode(*, _orig=orig, _calls=calls, _k=k, **kw):
            _calls[0] += 1
            if _calls[0] > plan.tick:
                if not state.fired:
                    state.fired = True
                    note("firing", on_replica=_k, delay_s=delay)
                sleep(delay)
            return _orig(**kw)

        eng.decode = decode
    return state


__all__ = ["DEGRADED", "HEALTHY", "HealthConfig", "HealthTracker",
           "PROBATION", "QUARANTINED", "ServeFaultState",
           "install_serve_fault"]
