"""``DecodeEngine`` — per-slot continuous batching over the GPT decode model.

The offline decode stack (``models/gpt.py: generate``) runs one fixed batch
start-to-finish: a single long request holds the whole batch hostage while
finished rows idle. This engine keeps the same fixed-shape/pjit discipline
but makes the batch dimension a SLOT pool: every row of the KV cache is an
independent request at its own position (``GPTConfig.slot_decode`` — the
``cache_index`` variable is per-row), so requests stream in and out of rows
while the shapes never change.

Exactly two jitted programs exist, both AOT-compiled at construction:

- ``prefill_into_slot(slot, chunk, ...)`` — one fixed-width prompt chunk
  into one slot. The slot's rows are sliced out of the engine state into a
  batch-1 PLAIN cache (scalar ``cache_index``) and run through the
  ``chunked_prefill`` cache-continuing model that offline
  ``generate(prefill_chunk=...)`` already uses; the ragged last chunk is
  right-padded and masked via the model's ``prefill_len`` (pad K/V never
  survives in the cache, the index advances by the valid count only). On
  the last chunk the program also samples the request's FIRST token —
  mirroring ``generate``'s split-then-pick exactly, so engine output is
  bit-compatible with offline decode per request.
- ``decode_all()`` — one masked token step across ALL slots
  (``slot_decode`` model), with per-slot temperature/top-k/top-p/eos
  applied through :func:`dtf_tpu.models.gpt.filter_logits_dynamic` under a
  per-slot rng stream (vmapped split-then-pick, the batch-1 ``generate``
  stream per slot).

Because both programs are compiled executables, steady state CANNOT
recompile — a shape change would be a loud call-site error, not a silent
retrace (``trace_counts`` exposes the per-program trace counters the fence
test pins). State donation is deliberately off: on backfilled pre-0.5 jax a
donated executable deserialized from the persistent compile cache drops
aliased outputs (see core/train.py's gate and the conftest note).

Sharded serving: pass ``mesh`` and TP-sharded params — the cache lands
``P('data','model')`` (:func:`dtf_tpu.models.gpt.cache_shardings`: slots
over data shards, heads over TP shards) and the decode step runs under
GSPMD; the analysis registry's ``gpt_serve`` config fences the DECODE
graph's collectives (:func:`decode_step_view`) — the per-token hot path.
Known cost, not fenced: the sharded PREFILL dynamic-slices one slot out of
the data-sharded batch axis with a traced index, which GSPMD spells as a
resharding of the touched cache leaves per chunk — acceptable while
prefill is chunk-bounded and rare relative to decode steps, but a
per-shard slot-arithmetic shard_map is the upgrade path if sharded prefill
ever dominates (docs/SERVING.md).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dtf_tpu.models import gpt

PyTree = Any

#: engine state keys that are flat per-slot arrays (leading dim n_slots),
#: next to the "cache" collection. One registry so the state builder, the
#: abstract view and the programs cannot desynchronize.
_SLOT_ARRAYS = (
    ("tok", jnp.int32),     # last emitted token (next decode input)
    ("temp", jnp.float32),  # 0 = greedy, else sampling temperature
    ("top_k", jnp.int32),   # 0 = off
    ("top_p", jnp.float32),  # 1.0 = off
    ("eos", jnp.int32),     # -1 = no stop token
    ("pad", jnp.int32),     # token emitted after eos (offline parity)
    ("done", jnp.bool_),    # has emitted eos
    ("active", jnp.bool_),  # fully prefilled; a False row (empty slot or
                            # mid-prefill between interleaved chunks) rides
                            # the decode step untouched: no cache write, no
                            # index advance, no rng consumption
)


def _leaf_name(path) -> str:
    return getattr(path[-1], "key", str(path[-1]))


def _slice_slot_cache(cache: PyTree, slot) -> PyTree:
    """One slot's rows as a batch-1 PLAIN cache (scalar ``cache_index``)
    for the ``chunked_prefill`` model. Leaves are selected by key path —
    the same completeness contract as beam search's reorder
    (``gpt._BATCH_LED_CACHE_KEYS``): an unknown leaf fails loudly instead
    of silently riding the slot un-sliced."""
    def leaf(path, x):
        name = _leaf_name(path)
        if name in gpt._BATCH_LED_CACHE_KEYS:
            return jax.lax.dynamic_slice_in_dim(x, slot, 1, axis=0)
        if name == "cache_index":
            return jax.lax.dynamic_slice_in_dim(x, slot, 1, axis=0)[0]
        raise ValueError(
            f"unknown cache leaf {name!r}: teach serve/engine.py how to "
            "slice it per slot (see gpt._BATCH_LED_CACHE_KEYS)")

    return jax.tree_util.tree_map_with_path(leaf, cache)


def _write_slot_cache(cache: PyTree, row: PyTree, slot) -> PyTree:
    """Write a batch-1 plain cache back into slot ``slot``."""
    def leaf(path, x, r):
        name = _leaf_name(path)
        if name in gpt._BATCH_LED_CACHE_KEYS:
            return jax.lax.dynamic_update_slice_in_dim(x, r, slot, axis=0)
        if name == "cache_index":
            return jax.lax.dynamic_update_slice_in_dim(
                x, r[None], slot, axis=0)
        raise ValueError(f"unknown cache leaf {name!r}")

    return jax.tree_util.tree_map_with_path(leaf, cache, row)


def _pick(sub, logits_v, temp, top_k, top_p):
    """One slot's token pick — ``generate``'s ``pick`` at batch-1 shapes
    ([1,V] through the filter, [0] out), so the sampled stream is
    bit-identical to an offline batch-1 ``generate`` with the same rng."""
    safe_t = jnp.where(temp > 0.0, temp, 1.0)
    filt = gpt.filter_logits_dynamic(logits_v[None, :] / safe_t,
                                     top_k=top_k, top_p=top_p)
    sampled = jax.random.categorical(sub, filt, -1)[0]
    greedy = jnp.argmax(logits_v[None, :], -1)[0]
    return jnp.where(temp > 0.0, sampled, greedy).astype(jnp.int32)


def _build_decode_fn(model: gpt.GPT):
    """decode_all: one masked token step across all slots."""
    def decode_fn(params, state):
        active = state["active"]
        logits, mut = model.apply(
            {"params": params, "cache": state["cache"]},
            state["tok"][:, None], deterministic=True, mutable=["cache"],
            decode_active=active)
        lg = logits[:, 0]                                    # [S, V] f32

        def one(key, lv, temp, tk, tp):
            s2 = jax.random.split(key)
            return s2[0], _pick(s2[1], lv, temp, tk, tp)

        rng, nxt = jax.vmap(one)(state["rng"], lg, state["temp"],
                                 state["top_k"], state["top_p"])
        # offline eos semantics per slot: a done row keeps stepping but
        # emits pad; done flips AFTER the eos token itself is kept.
        nxt = jnp.where(state["done"], state["pad"], nxt)
        done = state["done"] | ((state["eos"] >= 0) & (nxt == state["eos"]))
        # inactive rows are spectators: their rng/token/done rows must
        # survive the step bit-for-bit (a mid-prefill slot's rng stream is
        # the request's sampling stream — advancing it here would break
        # the offline-parity contract).
        new_state = {
            **state, "cache": mut["cache"],
            "rng": jnp.where(active[:, None], rng, state["rng"]),
            "tok": jnp.where(active, nxt, state["tok"]),
            "done": jnp.where(active, done, state["done"]),
        }
        return new_state, {"token": nxt, "done": done}

    return decode_fn


def _build_prefill_fn(model: gpt.GPT):
    """prefill_into_slot: one fixed-width chunk into one slot; on the last
    chunk, sample the request's first token (generate's split-then-pick)."""
    def prefill_fn(params, state, slot, chunk, n_valid, reset, is_last,
                   temp, top_k, top_p, eos, pad, key):
        cache = state["cache"]
        row = _slice_slot_cache(cache, slot)
        # a fresh request starts at index 0; stale slot contents need no
        # clearing (validity is derived from the index — gpt.py docstring)
        row = jax.tree_util.tree_map_with_path(
            lambda p, x: jnp.where(reset, jnp.zeros_like(x), x)
            if _leaf_name(p) == "cache_index" else x, row)
        logits, mut = model.apply(
            {"params": params, "cache": row}, chunk[None, :],
            deterministic=True, mutable=["cache"], prefill_len=n_valid)
        cache = _write_slot_cache(cache, mut["cache"], slot)

        # sampling-params rows are (re)stamped on every chunk of the
        # request — idempotent, and the slot is fully reinitialized by its
        # first chunk no matter who occupied it before.
        last = jax.lax.dynamic_index_in_dim(logits[0], n_valid - 1,
                                            axis=0, keepdims=False)  # [V]
        key_row = jnp.where(reset, key, state["rng"][slot])
        s2 = jax.random.split(key_row)
        tok_new = _pick(s2[1], last, temp, top_k, top_p)
        done_new = is_last & (eos >= 0) & (tok_new == eos)
        new_state = {
            **state,
            "cache": cache,
            "rng": state["rng"].at[slot].set(
                jnp.where(is_last, s2[0], key_row)),
            "tok": state["tok"].at[slot].set(
                jnp.where(is_last, tok_new, state["tok"][slot])),
            "temp": state["temp"].at[slot].set(temp),
            "top_k": state["top_k"].at[slot].set(top_k),
            "top_p": state["top_p"].at[slot].set(top_p),
            "eos": state["eos"].at[slot].set(eos),
            "pad": state["pad"].at[slot].set(pad),
            "done": state["done"].at[slot].set(done_new),
            # the slot joins decode_all only once its LAST chunk landed;
            # until then it is a masked spectator of the all-slots step
            "active": state["active"].at[slot].set(is_last),
        }
        return new_state, {"token": tok_new, "done": done_new}

    return prefill_fn


def _state_struct(cfg: gpt.GPTConfig, n_slots: int,
                  mesh: Optional[Mesh]) -> PyTree:
    """Abstract engine state (ShapeDtypeStructs, shardings when mesh):
    the slot-batched cache collection plus the flat per-slot arrays."""
    model = gpt.GPT(cfg, mesh)
    shapes = jax.eval_shape(lambda: model.init(
        jax.random.PRNGKey(0), jnp.zeros((n_slots, 1), jnp.int32)))
    cache = shapes["cache"]
    if mesh is not None:
        csh = gpt.cache_shardings(mesh, cache)
        cache = jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                               sharding=sh), cache, csh)
    rep = NamedSharding(mesh, P()) if mesh is not None else None

    def sds(shape, dtype):
        if rep is None:
            return jax.ShapeDtypeStruct(shape, dtype)
        return jax.ShapeDtypeStruct(shape, dtype, sharding=rep)

    state = {"cache": cache,
             "rng": sds((n_slots, 2), jnp.uint32)}
    for name, dtype in _SLOT_ARRAYS:
        state[name] = sds((n_slots,), dtype)
    return state


def _zeros_like_struct(struct: PyTree) -> PyTree:
    def leaf(s):
        sh = getattr(s, "sharding", None)
        if sh is not None:
            # sharding-aware allocation: each device materializes only its
            # shard (the same move as generate()'s sharded cache0)
            return jnp.zeros(s.shape, s.dtype, device=sh)
        return jnp.zeros(s.shape, s.dtype)

    return jax.tree.map(leaf, struct)


class DecodeEngine:
    """Slot-pooled online decode over a GPT checkpoint.

    ``cfg`` is the TRAINED architecture (decode fields are overridden
    here): ``max_len`` sizes the per-slot KV cache (prompt + generated
    tokens per request must fit), ``n_slots`` the concurrent-request pool,
    ``prefill_chunk`` the fixed width of the prefill program (>= 2 — a
    1-token apply would route to the decode branch). With ``mesh``, pass
    params already sharded (``shard_tree(params, mesh, gpt.tp_rules)``).
    """

    def __init__(self, cfg: gpt.GPTConfig, params: PyTree, *, n_slots: int,
                 max_len: int, prefill_chunk: int = 16,
                 mesh: Optional[Mesh] = None):
        if n_slots < 1:
            raise ValueError(f"n_slots={n_slots} must be >= 1")
        if max_len < 2:
            raise ValueError(f"max_len={max_len} must be >= 2 "
                             "(prompt + at least one generated token)")
        if prefill_chunk < 2:
            raise ValueError(
                f"prefill_chunk={prefill_chunk} must be >= 2: a 1-token "
                "apply routes to the single-token decode branch, not the "
                "chunked-prefill path")
        base = dataclasses.replace(cfg, decode_len=max_len,
                                   slot_decode=False, chunked_prefill=False)
        # the chunk may not be wider than ANY layer's cache: the rolling-
        # buffer write keeps only the last cache_len CHUNK positions, and
        # right-padding sits at the chunk's end — a wider chunk would push
        # valid prompt tokens out of the write window (their K/V silently
        # dropped, decode garbled with no shape error).
        min_cache = min(
            (min(max_len, w) if (w := base.layer_window(i)) else max_len)
            for i in range(base.layers))
        if prefill_chunk > min_cache:
            raise ValueError(
                f"prefill_chunk={prefill_chunk} exceeds the smallest "
                f"per-layer cache length {min_cache} (max_len={max_len}, "
                f"attn_window={base.attn_window}); a right-padded chunk "
                "wider than the cache drops valid prompt K/V")
        self.cfg = base
        self.n_slots = n_slots
        self.max_len = max_len
        self.prefill_chunk = prefill_chunk
        self.mesh = mesh
        if mesh is None:
            # a restored checkpoint carries the TRAINING mesh's shardings;
            # unsharded serving runs on one device, and the AOT-compiled
            # programs (unlike plain jit) reject mismatched input shardings
            # instead of re-lowering — commit params here once.
            dev = jax.devices()[0]
            params = jax.tree.map(lambda x: jax.device_put(x, dev), params)
        self._params = params
        self._decode_model = gpt.GPT(
            dataclasses.replace(base, slot_decode=True), mesh)
        self._prefill_model = gpt.GPT(
            dataclasses.replace(base, chunked_prefill=True), mesh)

        struct = _state_struct(dataclasses.replace(base, slot_decode=True),
                               n_slots, mesh)
        self._state = _zeros_like_struct(struct)
        # engine defaults that zeros get wrong: nucleus off, no stop token
        self._state["top_p"] = self._state["top_p"] + 1.0
        self._state["eos"] = self._state["eos"] - 1
        if mesh is not None:
            rep = NamedSharding(mesh, P())
            self._state["top_p"] = jax.device_put(self._state["top_p"], rep)
            self._state["eos"] = jax.device_put(self._state["eos"], rep)

        #: traces per program — the recompile fence. AOT compilation below
        #: traces each exactly once; any later increment would mean a
        #: shape-driven retrace, which the compiled executables make
        #: impossible by construction (they reject new shapes instead).
        self.trace_counts = {"prefill": 0, "decode": 0}
        decode_fn = _build_decode_fn(self._decode_model)
        prefill_fn = _build_prefill_fn(self._prefill_model)

        def counted(name, fn):
            def wrapped(*args):
                self.trace_counts[name] += 1
                return fn(*args)
            return wrapped

        abs_params = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype,
                sharding=x.sharding if mesh is not None else None),
            params)
        abs_state = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype,
                sharding=x.sharding if mesh is not None else None),
            self._state)
        s_i32 = jax.ShapeDtypeStruct((), jnp.int32)
        s_f32 = jax.ShapeDtypeStruct((), jnp.float32)
        s_bool = jax.ShapeDtypeStruct((), jnp.bool_)
        jit_kw = {}
        if mesh is not None:
            # pin the OUTPUT state to the input layout: GSPMD would
            # otherwise pick its own output shardings, and the next call
            # of the AOT executable would reject the resharded state
            rep = NamedSharding(mesh, P())
            state_sh = jax.tree.map(lambda s: s.sharding, abs_state)
            jit_kw["out_shardings"] = (state_sh,
                                       {"token": rep, "done": rep})
        self._decode_c = jax.jit(counted("decode", decode_fn),
                                 **jit_kw).lower(
            abs_params, abs_state).compile()
        self._prefill_c = jax.jit(counted("prefill", prefill_fn),
                                  **jit_kw).lower(
            abs_params, abs_state, s_i32,
            jax.ShapeDtypeStruct((prefill_chunk,), jnp.int32), s_i32,
            s_bool, s_bool, s_f32, s_i32, s_f32, s_i32, s_i32,
            jax.ShapeDtypeStruct((2,), jnp.uint32)).compile()

    # ------------------------------------------------------------- host API

    def n_chunks(self, prompt_len: int) -> int:
        return math.ceil(prompt_len / self.prefill_chunk)

    def prefill_chunk_into(self, slot: int, prompt: Sequence[int],
                           chunk_i: int, *, temperature: float = 0.0,
                           top_k: int = 0, top_p: float = 1.0,
                           eos_id: Optional[int] = None, pad_id: int = 0,
                           seed: int = 0) -> Optional[tuple[int, bool]]:
        """Run prompt chunk ``chunk_i`` of a request into ``slot`` — the
        scheduler's prefill/decode interleave granularity (decode_all may
        run between chunks; the slot stays a masked spectator until its
        last chunk lands). Returns ``(first_token, done)`` on the last
        chunk, None before."""
        prompt = list(int(t) for t in prompt)
        if not 1 <= len(prompt) <= self.max_len - 1:
            raise ValueError(
                f"prompt length {len(prompt)} must be in [1, "
                f"{self.max_len - 1}] (max_len={self.max_len} covers "
                "prompt + generated tokens)")
        if not 0 <= slot < self.n_slots:
            raise ValueError(f"slot {slot} out of range [0, {self.n_slots})")
        c = self.prefill_chunk
        n = self.n_chunks(len(prompt))
        if not 0 <= chunk_i < n:
            raise ValueError(f"chunk {chunk_i} out of range [0, {n})")
        seg = prompt[chunk_i * c:(chunk_i + 1) * c]
        buf = np.zeros((c,), np.int32)
        buf[:len(seg)] = seg
        last = chunk_i == n - 1
        self._state, out = self._prefill_c(
            self._params, self._state, np.int32(slot), buf,
            np.int32(len(seg)), np.bool_(chunk_i == 0), np.bool_(last),
            np.float32(temperature), np.int32(top_k), np.float32(top_p),
            np.int32(-1 if eos_id is None else eos_id), np.int32(pad_id),
            np.asarray(jax.random.PRNGKey(seed), np.uint32))
        if not last:
            return None
        return int(out["token"]), bool(out["done"])

    def prefill(self, slot: int, prompt: Sequence[int],
                **sampling) -> tuple[int, bool]:
        """Admit a request into ``slot``: stream its whole prompt through
        the compiled chunk program and sample the first token. Returns
        ``(first_token, done)``."""
        n = self.n_chunks(len(prompt))
        if n == 0:
            # the per-chunk validation never runs on an empty prompt —
            # fail here, not with a None return at the caller's unpack
            raise ValueError(
                f"prompt length 0 must be in [1, {self.max_len - 1}]")
        out = None
        for i in range(n):
            out = self.prefill_chunk_into(slot, prompt, i, **sampling)
        return out

    def decode(self) -> tuple[np.ndarray, np.ndarray]:
        """One masked token step across all slots. Returns
        ``(tokens [n_slots], done [n_slots])`` as host arrays — the one
        device→host sync per generated token (EOS and delivery decisions
        live on the host)."""
        self._state, out = self._decode_c(self._params, self._state)
        return np.asarray(out["token"]), np.asarray(out["done"])

    def cache_bytes(self) -> int:
        """Resident KV-cache footprint (all slots, all layers)."""
        return sum(int(np.prod(x.shape)) * x.dtype.itemsize
                   for x in jax.tree.leaves(self._state["cache"]))


def decode_step_view(cfg: gpt.GPTConfig, *, n_slots: int, max_len: int,
                     mesh: Optional[Mesh] = None):
    """The engine's decode program as an analyzable step:
    ``(jitted_fn, abstract_params, abstract_state)`` — what the analysis
    registry's ``gpt_serve`` config lowers so the comms-budget fence
    covers the serving decode graph exactly as ``DecodeEngine`` compiles
    it (same model, same state layout, same shardings)."""
    from dtf_tpu.core.sharding import tree_shardings

    dec_cfg = dataclasses.replace(cfg, decode_len=max_len, slot_decode=True)
    model = gpt.GPT(dec_cfg, mesh)
    step = jax.jit(_build_decode_fn(model))
    shapes = jax.eval_shape(lambda: model.init(
        jax.random.PRNGKey(0), jnp.zeros((n_slots, 1), jnp.int32)))
    abs_params = shapes["params"]
    if mesh is not None:
        abs_params = jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                               sharding=sh),
            abs_params, tree_shardings(abs_params, mesh, gpt.tp_rules))
    abs_state = _state_struct(dec_cfg, n_slots, mesh)
    return step, abs_params, abs_state
