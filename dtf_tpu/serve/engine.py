"""``DecodeEngine`` — per-slot continuous batching over the GPT decode model.

The offline decode stack (``models/gpt.py: generate``) runs one fixed batch
start-to-finish: a single long request holds the whole batch hostage while
finished rows idle. This engine keeps the same fixed-shape/pjit discipline
but makes the batch dimension a SLOT pool: every row of the KV cache is an
independent request at its own position (``GPTConfig.slot_decode`` — the
``cache_index`` variable is per-row), so requests stream in and out of rows
while the shapes never change.

Without a draft model, exactly two jitted programs exist, both
AOT-compiled at construction; with one (``draft_cfg``/``draft_params`` +
``spec_k`` — speculative decoding), exactly FOUR, never more:
``prefill``, ``decode/verify`` (ONE program — the (k+1)-wide verify step
IS spec decode; there is no separate single-token program), and the
draft twins ``draft_prefill`` / ``draft_all``. See the speculative
section below.

- ``prefill_into_slot(slot, chunk, ...)`` — one fixed-width prompt chunk
  into one slot. The slot's rows are sliced out of the engine state into a
  batch-1 PLAIN cache (scalar ``cache_index``) and run through the
  ``chunked_prefill`` cache-continuing model that offline
  ``generate(prefill_chunk=...)`` already uses; the ragged last chunk is
  right-padded and masked via the model's ``prefill_len`` (pad K/V never
  survives in the cache, the index advances by the valid count only). On
  the last chunk the program also samples the request's FIRST token —
  mirroring ``generate``'s split-then-pick exactly, so engine output is
  bit-compatible with offline decode per request.
- ``decode_all()`` — one masked token step across ALL slots
  (``slot_decode`` model), with per-slot temperature/top-k/top-p/eos
  applied through :func:`dtf_tpu.models.gpt.filter_logits_dynamic` under a
  per-slot rng stream (vmapped split-then-pick, the batch-1 ``generate``
  stream per slot).

With ``prefix_pages > 0`` the engine additionally keeps a device **page
pool** and two more AOT programs, ``page_save``/``page_load`` (fixed-shape
BATCHED copies of a slot's page set to/from the pool, one dispatch per
admission — see :mod:`dtf_tpu.serve.pages` and
:func:`dtf_tpu.models.gpt.cache_load_pages`); the decode/prefill programs
are untouched, so
``trace_counts`` stays pinned at ``{prefill: 1, decode: 1}`` and the page
programs carry their own ``page_trace_counts`` fence.

**Speculative decoding** (``spec_k > 0``): each tick is ``draft_all``
(the small draft model proposes k greedy tokens per active slot, one
dispatch, its own slot cache) followed by ``decode/verify`` (the target
scores all k+1 positions in one masked pass — the model's slot-verify
branch — samples its OWN token per position through the row's rng
stream, and accepts the longest proposal prefix matching those samples:
``n_emit = 1 + |match|`` tokens per slot per tick, cache index rolled
back to the accepted boundary per row, rejected-tail KV left masked by
the validity bias). Token streams are IDENTICAL to non-speculative
decode (greedy and seeded sampling alike — the verifier's samples are
the stream; proposals only decide how many positions per dispatch are
worth keeping), pinned by tests/test_serve_spec.py. The draft's cache
stays in sync through host-mirrored ``(tok, index)`` operands that ride
readbacks decode performs anyway; the draft never touches the page pool
(its prefill always covers the full prompt). A draft failure falls back
to verify-with-null-proposals — plain decode — instead of erroring
requests.

Because all programs are compiled executables, steady state CANNOT
recompile — a shape change would be a loud call-site error, not a silent
retrace (``trace_counts`` exposes the per-program trace counters the fence
test pins). State donation is deliberately off: on backfilled pre-0.5 jax a
donated executable deserialized from the persistent compile cache drops
aliased outputs (see core/train.py's gate and the conftest note).

Sharded serving: pass ``mesh`` and TP-sharded params — the cache lands
``P('data','model')`` (:func:`dtf_tpu.models.gpt.cache_shardings`: slots
over data shards, heads over TP shards) and the decode step runs under
GSPMD; the analysis registry's ``gpt_serve`` config fences the DECODE
graph's collectives (:func:`decode_step_view`) — the per-token hot path.
Known cost, not fenced: the sharded PREFILL dynamic-slices one slot out of
the data-sharded batch axis with a traced index, which GSPMD spells as a
resharding of the touched cache leaves per chunk — acceptable while
prefill is chunk-bounded and rare relative to decode steps, but a
per-shard slot-arithmetic shard_map is the upgrade path if sharded prefill
ever dominates (docs/SERVING.md).
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import math
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dtf_tpu.core import executor
from dtf_tpu.models import gpt

log = logging.getLogger("dtf_tpu")

PyTree = Any

#: engine state keys that are flat per-slot arrays (leading dim n_slots),
#: next to the "cache" collection. One registry so the state builder, the
#: abstract view and the programs cannot desynchronize.
_SLOT_ARRAYS = (
    ("tok", jnp.int32),     # last emitted token (next decode input)
    ("temp", jnp.float32),  # 0 = greedy, else sampling temperature
    ("top_k", jnp.int32),   # 0 = off
    ("top_p", jnp.float32),  # 1.0 = off
    ("eos", jnp.int32),     # -1 = no stop token
    ("pad", jnp.int32),     # token emitted after eos (offline parity)
    ("done", jnp.bool_),    # has emitted eos
    ("active", jnp.bool_),  # fully prefilled; a False row (empty slot or
                            # mid-prefill between interleaved chunks) rides
                            # the decode step untouched: no cache write, no
                            # index advance, no rng consumption
)


def _leaf_name(path) -> str:
    return getattr(path[-1], "key", str(path[-1]))


def _slice_slot_cache(cache: PyTree, slot) -> PyTree:
    """One slot's rows as a batch-1 PLAIN cache (scalar ``cache_index``)
    for the ``chunked_prefill`` model. Leaves are selected by key path —
    the same completeness contract as beam search's reorder
    (``gpt._BATCH_LED_CACHE_KEYS``): an unknown leaf fails loudly instead
    of silently riding the slot un-sliced."""
    def leaf(path, x):
        name = _leaf_name(path)
        if name in gpt._BATCH_LED_CACHE_KEYS:
            return jax.lax.dynamic_slice_in_dim(x, slot, 1, axis=0)
        if name == "cache_index":
            return jax.lax.dynamic_slice_in_dim(x, slot, 1, axis=0)[0]
        raise ValueError(
            f"unknown cache leaf {name!r}: teach serve/engine.py how to "
            "slice it per slot (see gpt._BATCH_LED_CACHE_KEYS)")

    return jax.tree_util.tree_map_with_path(leaf, cache)


def _write_slot_cache(cache: PyTree, row: PyTree, slot) -> PyTree:
    """Write a batch-1 plain cache back into slot ``slot``."""
    def leaf(path, x, r):
        name = _leaf_name(path)
        if name in gpt._BATCH_LED_CACHE_KEYS:
            return jax.lax.dynamic_update_slice_in_dim(x, r, slot, axis=0)
        if name == "cache_index":
            return jax.lax.dynamic_update_slice_in_dim(
                x, r[None], slot, axis=0)
        raise ValueError(f"unknown cache leaf {name!r}")

    return jax.tree_util.tree_map_with_path(leaf, cache, row)


def _pick(sub, logits_v, temp, top_k, top_p):
    """One slot's token pick — ``generate``'s ``pick`` at batch-1 shapes
    ([1,V] through the filter, [0] out), so the sampled stream is
    bit-identical to an offline batch-1 ``generate`` with the same rng."""
    safe_t = jnp.where(temp > 0.0, temp, 1.0)
    filt = gpt.filter_logits_dynamic(logits_v[None, :] / safe_t,
                                     top_k=top_k, top_p=top_p)
    sampled = jax.random.categorical(sub, filt, -1)[0]
    greedy = jnp.argmax(logits_v[None, :], -1)[0]
    return jnp.where(temp > 0.0, sampled, greedy).astype(jnp.int32)


def _build_decode_fn(model: gpt.GPT):
    """decode_all: one masked token step across all slots."""
    def decode_fn(params, state):
        active = state["active"]
        logits, mut = model.apply(
            {"params": params, "cache": state["cache"]},
            state["tok"][:, None], deterministic=True, mutable=["cache"],
            decode_active=active)
        lg = logits[:, 0]                                    # [S, V] f32

        def one(key, lv, temp, tk, tp):
            s2 = jax.random.split(key)
            return s2[0], _pick(s2[1], lv, temp, tk, tp)

        rng, nxt = jax.vmap(one)(state["rng"], lg, state["temp"],
                                 state["top_k"], state["top_p"])
        # offline eos semantics per slot: a done row keeps stepping but
        # emits pad; done flips AFTER the eos token itself is kept.
        nxt = jnp.where(state["done"], state["pad"], nxt)
        done = state["done"] | ((state["eos"] >= 0) & (nxt == state["eos"]))
        # inactive rows are spectators: their rng/token/done rows must
        # survive the step bit-for-bit (a mid-prefill slot's rng stream is
        # the request's sampling stream — advancing it here would break
        # the offline-parity contract).
        new_state = {
            **state, "cache": mut["cache"],
            "rng": jnp.where(active[:, None], rng, state["rng"]),
            "tok": jnp.where(active, nxt, state["tok"]),
            "done": jnp.where(active, done, state["done"]),
        }
        return new_state, {"token": nxt, "done": done}

    return decode_fn


def _build_draft_fn(model: gpt.GPT, k: int):
    """draft_all: k GREEDY proposals per active slot in ONE dispatch — an
    unrolled loop of single-token ``slot_decode`` steps of the (small)
    draft model, writing the draft's own KV cache as it goes. Greedy on
    purpose: proposals are guesses the verifier prefix-matches against
    its own sampled stream, so they carry no rng and no sampling params —
    the draft's job is to be RIGHT often, not random. ``sync_index``
    (host-tracked by the engine) first rolls every active row's draft
    cache index to the verifier's accepted boundary, so rejected
    proposals from the last tick are forgotten the same way the
    verifier's are: by index assignment, never by clearing."""
    def draft_fn(params, state, tok, sync_index):
        active = state["active"]
        cache = gpt.cache_rollback(state["cache"], sync_index, active=active)
        cur = tok
        props = []
        # k+1 steps for k proposals: the LAST step ingests d_k itself
        # (output discarded), so on a clean sweep — where the verifier
        # advances k+1 positions (k matches + the bonus token) — the
        # draft cache has no hole at position idx+k. Without it, every
        # full acceptance would leave one permanently unwritten position
        # behind the rolled-forward index, quietly poisoning all later
        # proposals for that slot.
        for _ in range(k + 1):
            logits, mut = model.apply(
                {"params": params, "cache": cache}, cur[:, None],
                deterministic=True, mutable=["cache"], decode_active=active)
            cache = mut["cache"]
            cur = jnp.argmax(logits[:, 0], -1).astype(jnp.int32)
            if len(props) < k:
                props.append(cur)
        return {**state, "cache": cache}, jnp.stack(props, axis=1)

    return draft_fn


def _build_verify_fn(model: gpt.GPT, k: int):
    """decode/verify: ONE (k+1)-token masked step across all slots — the
    speculative replacement for :func:`_build_decode_fn`'s single-token
    program (a spec engine compiles this under the same ``decode`` trace
    fence; there is no separate plain-decode program).

    Inputs per row: the pending token plus the k draft proposals. The
    model's slot-verify branch scores every position against the row's
    own cache; the verifier then samples its OWN token at each position
    through the row's rng stream — exactly one ``jax.random.split`` per
    EMITTED token, the same chain sequential decode consumes, with the
    same eos→pad freezing per position. Acceptance is a per-row PREFIX
    MATCH of the proposals against those sampled tokens: ``n_emit = 1 +
    |matching prefix|`` (position j+1's logits are only valid when
    inputs 1..j matched the emitted stream, which the prefix rule
    guarantees; the +1 is the verifier's own token — the correction on a
    mismatch, the bonus on a clean sweep). The cache index rolls back to
    the accepted boundary per row (:func:`gpt.cache_rollback`); rng/tok/
    done select the ``n_emit``-th chain entry, so a spec engine's visible
    state after a tick is what ``n_emit`` sequential decode steps would
    have left. Correct for ARBITRARY proposals (worst case n_emit = 1,
    i.e. plain decode) — the draft-failure fallback rides that."""
    def verify_fn(params, state, proposals):
        active = state["active"]
        idx0 = gpt.cache_index_of(state["cache"])              # [S]
        inputs = jnp.concatenate([state["tok"][:, None], proposals], axis=1)
        logits, mut = model.apply(
            {"params": params, "cache": state["cache"]}, inputs,
            deterministic=True, mutable=["cache"], decode_active=active)

        def one(key, lv, temp, tk, tp, eos, pad, done0):
            # the row's rng/eos chain, unrolled k+1 deep: entry j is what
            # the j-th sequential decode step would have sampled/split
            toks, dones, keys = [], [], [key]
            done, cur = done0, key
            for j in range(k + 1):
                s2 = jax.random.split(cur)
                v = _pick(s2[1], lv[j], temp, tk, tp)
                tkn = jnp.where(done, pad, v)
                done = done | ((eos >= 0) & (tkn == eos))
                toks.append(tkn)
                dones.append(done)
                keys.append(s2[0])
                cur = s2[0]
            return jnp.stack(toks), jnp.stack(dones), jnp.stack(keys)

        toks, dones, keys = jax.vmap(one)(
            state["rng"], logits, state["temp"], state["top_k"],
            state["top_p"], state["eos"], state["pad"], state["done"])
        match = jnp.cumprod((toks[:, :k] == proposals).astype(jnp.int32),
                            axis=1)
        n_emit = jnp.where(active, 1 + match.sum(axis=1),
                           0)                                   # [S] 0..k+1
        last = jnp.maximum(n_emit, 1) - 1
        new_tok = jnp.take_along_axis(toks, last[:, None], axis=1)[:, 0]
        new_done = jnp.take_along_axis(dones, last[:, None], axis=1)[:, 0]
        new_rng = jnp.take_along_axis(keys, n_emit[:, None, None],
                                      axis=1)[:, 0]
        cache = gpt.cache_rollback(mut["cache"], idx0 + n_emit,
                                   active=active)
        new_state = {
            **state, "cache": cache,
            "rng": jnp.where(active[:, None], new_rng, state["rng"]),
            "tok": jnp.where(active, new_tok, state["tok"]),
            "done": jnp.where(active, new_done, state["done"]),
        }
        return new_state, {"tokens": toks, "done": dones, "n_emit": n_emit}

    return verify_fn


def _build_prefill_fn(model: gpt.GPT):
    """prefill_into_slot: one fixed-width chunk into one slot; on the last
    chunk, sample the request's first token (generate's split-then-pick).
    ``start`` is the number of already-valid leading positions (0 for a
    plain request; the prefix-page count × page size after page loads) —
    the reset lands the slot's index there, so the live chunks CONTINUE
    the loaded pages exactly like offline chunked prefill continues an
    advanced cache."""
    def prefill_fn(params, state, slot, start, chunk, n_valid, reset,
                   is_last, temp, top_k, top_p, eos, pad, key):
        cache = state["cache"]
        row = _slice_slot_cache(cache, slot)
        # a fresh request starts at index `start` (0 without prefix pages;
        # stale slot contents past it need no clearing — validity is
        # derived from the index, gpt.py docstring)
        row = jax.tree_util.tree_map_with_path(
            lambda p, x: jnp.where(reset, jnp.asarray(start, x.dtype), x)
            if _leaf_name(p) == "cache_index" else x, row)
        logits, mut = model.apply(
            {"params": params, "cache": row}, chunk[None, :],
            deterministic=True, mutable=["cache"], prefill_len=n_valid)
        cache = _write_slot_cache(cache, mut["cache"], slot)

        # sampling-params rows are (re)stamped on every chunk of the
        # request — idempotent, and the slot is fully reinitialized by its
        # first chunk no matter who occupied it before.
        last = jax.lax.dynamic_index_in_dim(logits[0], n_valid - 1,
                                            axis=0, keepdims=False)  # [V]
        key_row = jnp.where(reset, key, state["rng"][slot])
        s2 = jax.random.split(key_row)
        tok_new = _pick(s2[1], last, temp, top_k, top_p)
        done_new = is_last & (eos >= 0) & (tok_new == eos)
        new_state = {
            **state,
            "cache": cache,
            "rng": state["rng"].at[slot].set(
                jnp.where(is_last, s2[0], key_row)),
            "tok": state["tok"].at[slot].set(
                jnp.where(is_last, tok_new, state["tok"][slot])),
            "temp": state["temp"].at[slot].set(temp),
            "top_k": state["top_k"].at[slot].set(top_k),
            "top_p": state["top_p"].at[slot].set(top_p),
            "eos": state["eos"].at[slot].set(eos),
            "pad": state["pad"].at[slot].set(pad),
            "done": state["done"].at[slot].set(done_new),
            # the slot joins decode_all only once its LAST chunk landed;
            # until then it is a masked spectator of the all-slots step
            "active": state["active"].at[slot].set(is_last),
        }
        return new_state, {"token": tok_new, "done": done_new}

    return prefill_fn


def _build_page_save_fn(n_pages: int):
    """page_save: scatter the NEW pages of one slot's prompt — page j in
    ``[lo, hi)`` lands at pool entry ``page_ids[j]`` — in one dispatch
    (a per-page program would pay as much host overhead as the prefill
    chunks the cache saves). Pages outside the window are pointed at the
    out-of-range sentinel, which drop-mode scatter discards."""
    def save_fn(state, pool, slot, page_ids, lo, hi):
        m = page_ids.shape[0]
        j = jnp.arange(m)
        ids = jnp.where((j >= lo) & (j < hi), page_ids, n_pages)
        return gpt.cache_save_pages(state["cache"], pool, slot, ids)

    return save_fn


def _build_page_load_fn():
    """page_load: gather a whole pinned page chain (``page_ids[:n_valid]``)
    into the leading positions of one slot — and DEACTIVATE the slot. The
    deactivate matters: a freshly admitted slot still carries its previous
    occupant's ``active``/index rows, and a decode_all running before the
    first live chunk would otherwise keep writing the old request's
    garbage K/V over the pages just landed."""
    def load_fn(state, pool, slot, page_ids, n_valid):
        return {
            **state,
            "cache": gpt.cache_load_pages(state["cache"], pool, slot,
                                          page_ids, n_valid),
            "active": state["active"].at[slot].set(False),
            "done": state["done"].at[slot].set(False),
        }

    return load_fn


def _state_struct(cfg: gpt.GPTConfig, n_slots: int,
                  mesh: Optional[Mesh]) -> PyTree:
    """Abstract engine state (ShapeDtypeStructs, shardings when mesh):
    the slot-batched cache collection plus the flat per-slot arrays."""
    model = gpt.GPT(cfg, mesh)
    shapes = jax.eval_shape(lambda: model.init(
        jax.random.PRNGKey(0), jnp.zeros((n_slots, 1), jnp.int32)))
    cache = shapes["cache"]
    if mesh is not None:
        csh = gpt.cache_shardings(mesh, cache)
        cache = jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                               sharding=sh), cache, csh)
    rep = NamedSharding(mesh, P()) if mesh is not None else None

    def sds(shape, dtype):
        if rep is None:
            return jax.ShapeDtypeStruct(shape, dtype)
        return jax.ShapeDtypeStruct(shape, dtype, sharding=rep)

    state = {"cache": cache,
             "rng": sds((n_slots, 2), jnp.uint32)}
    for name, dtype in _SLOT_ARRAYS:
        state[name] = sds((n_slots,), dtype)
    return state


def _zeros_like_struct(struct: PyTree) -> PyTree:
    def leaf(s):
        sh = getattr(s, "sharding", None)
        if sh is not None:
            # sharding-aware allocation: each device materializes only its
            # shard (the same move as generate()'s sharded cache0)
            return jnp.zeros(s.shape, s.dtype, device=sh)
        return jnp.zeros(s.shape, s.dtype)

    return jax.tree.map(leaf, struct)


def _cfg_label(cfg: gpt.GPTConfig) -> str:
    """A compact architecture identity for tune-cache keys — enough to
    distinguish model/draft pairs without serializing the whole config."""
    return (f"d{cfg.d_model}L{cfg.layers}h{cfg.heads}"
            f"kv{cfg.kv_heads_resolved}v{cfg.vocab_size}")


class DecodeEngine:
    """Slot-pooled online decode over a GPT checkpoint.

    ``cfg`` is the TRAINED architecture (decode fields are overridden
    here): ``max_len`` sizes the per-slot KV cache (prompt + generated
    tokens per request must fit), ``n_slots`` the concurrent-request pool,
    ``prefill_chunk`` the fixed width of the prefill program (>= 2 — a
    1-token apply would route to the decode branch). With ``mesh``, pass
    params already sharded (``shard_tree(params, mesh, gpt.tp_rules)``).
    """

    def __init__(self, cfg: gpt.GPTConfig, params: PyTree, *, n_slots: int,
                 max_len: int, prefill_chunk: int = 16,
                 mesh: Optional[Mesh] = None, kv_page_size: int = 0,
                 prefix_pages: int = 0, page_save_after: int = 2,
                 draft_cfg: Optional[gpt.GPTConfig] = None,
                 draft_params: PyTree = None, spec_k: int = 0,
                 shared_pages=None):
        if n_slots < 1:
            raise ValueError(f"n_slots={n_slots} must be >= 1")
        if max_len < 2:
            raise ValueError(f"max_len={max_len} must be >= 2 "
                             "(prompt + at least one generated token)")
        if prefill_chunk < 2:
            raise ValueError(
                f"prefill_chunk={prefill_chunk} must be >= 2: a 1-token "
                "apply routes to the single-token decode branch, not the "
                "chunked-prefill path")
        if prefix_pages:
            if kv_page_size < 1:
                raise ValueError(
                    f"prefix_pages={prefix_pages} needs kv_page_size >= 1 "
                    f"(got {kv_page_size})")
            if max_len % kv_page_size:
                raise ValueError(
                    f"kv_page_size={kv_page_size} does not divide the "
                    f"cache length max_len={max_len}: a page window "
                    "crossing the cache end cannot be copied fixed-shape")
            if cfg.attn_window:
                raise ValueError(
                    f"the prefix page cache needs the plain slot=position "
                    f"cache layout; attn_window={cfg.attn_window} rolls "
                    "the buffer so page windows alias arbitrary positions")
        base = dataclasses.replace(cfg, decode_len=max_len,
                                   slot_decode=False, chunked_prefill=False)
        # the chunk may not be wider than ANY layer's cache: the rolling-
        # buffer write keeps only the last cache_len CHUNK positions, and
        # right-padding sits at the chunk's end — a wider chunk would push
        # valid prompt tokens out of the write window (their K/V silently
        # dropped, decode garbled with no shape error).
        min_cache = min(
            (min(max_len, w) if (w := base.layer_window(i)) else max_len)
            for i in range(base.layers))
        if prefill_chunk > min_cache:
            raise ValueError(
                f"prefill_chunk={prefill_chunk} exceeds the smallest "
                f"per-layer cache length {min_cache} (max_len={max_len}, "
                f"attn_window={base.attn_window}); a right-padded chunk "
                "wider than the cache drops valid prompt K/V")
        self.cfg = base
        self.n_slots = n_slots
        self.max_len = max_len
        self.prefill_chunk = prefill_chunk
        self.page_size = kv_page_size if prefix_pages else 0
        self.n_pages = prefix_pages
        self.mesh = mesh

        # ---- speculative decoding (draft model + verify step) -------------
        # spec_k == 0 with a draft present = "tuner decides" (the block-
        # shape sentinel contract, dtf_tpu/tune): the banked per-(model,
        # draft, slots) winner resolves the width; an explicit spec_k wins
        # with a warn-once when it overrides a MEASURED winner.
        if spec_k < 0:
            raise ValueError(f"spec_k={spec_k} must be >= 0")
        if spec_k and draft_cfg is None:
            raise ValueError(
                f"spec_k={spec_k} needs a draft model: pass draft_cfg + "
                "draft_params (speculation verifies a second model's "
                "proposals — there is nothing to verify without one)")
        self.spec_k = 0
        self.draft_cfg: Optional[gpt.GPTConfig] = None
        if draft_cfg is not None:
            if draft_params is None:
                raise ValueError("draft_cfg without draft_params")
            if base.attn_window or draft_cfg.attn_window:
                raise ValueError(
                    "speculative decoding needs the full windowless cache "
                    "layout on BOTH models (rolled buffers cannot roll a "
                    f"rejected tail back); got attn_window="
                    f"{base.attn_window}/{draft_cfg.attn_window}")
            if draft_cfg.vocab_size != base.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_cfg.vocab_size} != target vocab "
                    f"{base.vocab_size}: a draft must propose in the "
                    "verifier's token space")
            from dtf_tpu.tune import resolver as tune_resolver

            plan = tune_resolver.spec_k_plan(
                model=_cfg_label(base), draft=_cfg_label(draft_cfg),
                n_slots=n_slots,
                backend=jax.default_backend())
            if spec_k == 0:
                self.spec_k = plan.k
            else:
                self.spec_k = spec_k
                tune_resolver.note_override(
                    "spec_k", "k", spec_k, plan.k,
                    source=plan.source, measured=plan.measured)
            if self.spec_k + 1 >= max_len:
                raise ValueError(
                    f"spec_k={self.spec_k} leaves no room in the "
                    f"max_len={max_len} cache for a verify window")

        #: host-side call counters (plain ints — zero device readbacks):
        #: the bench/telemetry surface for "how much prefill work ran".
        self.counters = {"prefill_chunks": 0, "decode_steps": 0,
                         "pages_loaded": 0, "pages_saved": 0,
                         "prefix_hit_tokens": 0, "prefix_miss_tokens": 0,
                         "probe_decodes": 0, "param_swaps": 0}
        #: the param VERSION this engine serves (ISSUE 14 hot-swap):
        #: monotone, bumped by :meth:`swap_params`, stamped into every
        #: completed record by the scheduler and used as the prefix-page
        #: EPOCH so a cached stem can never serve stale-weight KV. 0 is
        #: "as constructed"; launchers serving a published version stamp
        #: it via :meth:`set_param_version` before traffic.
        self.param_version = 0
        if self.spec_k:
            # acceptance/fallback accounting: proposed counts k per LIVE
            # verified row per tick, accepted counts the matched prefix
            # (n_emit - 1); stale still-active rows ride both sides, so
            # the scheduler's per-running-slot rollup is the exact one.
            self.counters.update({"draft_steps": 0,
                                  "draft_prefill_chunks": 0,
                                  "draft_fallbacks": 0,
                                  "spec_proposed": 0, "spec_accepted": 0})
        #: when True, each compiled-program dispatch is wrapped in a
        #: jax.profiler.TraceAnnotation carrying the request trace id(s) the
        #: scheduler threaded down — a ProfilerHook window over a serving
        #: run then shows WHICH requests each prefill/decode dispatch
        #: served, joinable to the per-request chrome trace. Off by
        #: default: a TraceMe outside any profiling session is cheap but
        #: not free, and the id strings allocate per decode step.
        self.annotate_traces = False
        if mesh is None:
            # a restored checkpoint carries the TRAINING mesh's shardings;
            # unsharded serving runs on one device, and the AOT-compiled
            # programs (unlike plain jit) reject mismatched input shardings
            # instead of re-lowering — commit params here once.
            dev = jax.devices()[0]
            params = jax.tree.map(lambda x: jax.device_put(x, dev), params)
            if self.spec_k:
                draft_params = jax.tree.map(
                    lambda x: jax.device_put(x, dev), draft_params)
        self._params = params

        struct = _state_struct(dataclasses.replace(base, slot_decode=True),
                               n_slots, mesh)
        self._state = _zeros_like_struct(struct)
        # engine defaults that zeros get wrong: nucleus off, no stop token
        self._state["top_p"] = self._state["top_p"] + 1.0
        self._state["eos"] = self._state["eos"] - 1
        if mesh is not None:
            rep = NamedSharding(mesh, P())
            self._state["top_p"] = jax.device_put(self._state["top_p"], rep)
            self._state["eos"] = jax.device_put(self._state["eos"], rep)

        #: traces per program — the recompile fence. AOT compilation below
        #: traces each exactly once; any later increment would mean a
        #: shape-driven retrace, which the compiled executables make
        #: impossible by construction (they reject new shapes instead).
        #: With a draft model there are exactly FOUR programs — prefill,
        #: decode/verify (ONE program: the verify step IS spec decode),
        #: draft_prefill, draft — and the fence pins all four.
        self.trace_counts = {"prefill": 0, "decode": 0}

        def abs_of(tree):
            return jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(
                    x.shape, x.dtype,
                    sharding=x.sharding if mesh is not None else None),
                tree)

        abs_params = abs_of(params)
        abs_state = abs_of(self._state)
        abs_trees = {"params": abs_params, "state": abs_state}
        if self.spec_k:
            self.trace_counts.update({"draft_prefill": 0, "draft": 0})
            dbase = dataclasses.replace(
                draft_cfg, decode_len=max_len, slot_decode=False,
                chunked_prefill=False)
            self.draft_cfg = dbase
            self._draft_params = draft_params
            dstruct = _state_struct(
                dataclasses.replace(dbase, slot_decode=True), n_slots, mesh)
            self._draft_state = _zeros_like_struct(dstruct)
            abs_trees["draft_params"] = abs_of(draft_params)
            abs_trees["draft_state"] = abs_of(self._draft_state)
        #: the serve program table: every program born fenced through
        #: dtf_tpu/core/executor.py — the SAME construction the analysis
        #: step views enumerate, with this engine's abstract trees (real
        #: array shardings: restored checkpoints keep their layouts).
        self.programs, models = program_table(
            base, n_slots=n_slots, max_len=max_len, mesh=mesh,
            prefill_chunk=prefill_chunk, spec_k=self.spec_k,
            draft_cfg=self.draft_cfg, counts=self.trace_counts,
            abs_trees=abs_trees)
        self._decode_model = models["decode"]
        self._prefill_model = models["prefill"]
        self._decode_c = self.programs["decode"].aot()
        self._prefill_c = self.programs["prefill"].aot()

        if self.spec_k:
            self._draft_decode_model = models["draft"]
            self._draft_prefill_model = models["draft_prefill"]
            self._draft_prefill_c = self.programs["draft_prefill"].aot()
            self._draft_c = self.programs["draft"].aot()
            #: host mirrors of the verifier's per-slot position and
            #: pending token (fed to draft_all as sync operands): updated
            #: from values decode() reads back ANYWAY (tokens/n_emit), so
            #: speculation adds zero extra device readbacks per tick.
            self._spec_tok = np.zeros((n_slots,), np.int32)
            self._spec_index = np.zeros((n_slots,), np.int32)
            self._draft_chunks = np.zeros((n_slots,), np.int32)
            #: SELF-speculation (draft ≡ target architecture): the draft
            #: cache is struct-identical to the target's, so the page
            #: programs accept it and a prefix-page hit shortcuts the
            #: DRAFT prefill too (same weights ⇒ the pooled KV is the
            #: draft's KV). With a distinct draft model the pool holds
            #: foreign KV and the draft always prefills the full prompt.
            self._draft_self = dbase == base
            self._draft_start = np.zeros((n_slots,), np.int32)
            self._draft_pending = np.zeros((n_slots,), np.int32)
            if self._draft_self:
                self.counters["draft_pages_loaded"] = 0

        #: the prefix page cache (None unless prefix_pages > 0): device
        #: pool + host index + two more AOT programs with their own trace
        #: fence — trace_counts itself stays pinned at {prefill, decode}.
        #: ``shared_pages`` mounts another engine's :class:`PageStore`
        #: instead of allocating — the disaggregation KV transport: pages a
        #: prefill replica saves are immediately loadable by every decode
        #: replica mounting the same store.
        self._page_store = None
        self.page_trace_counts = {}
        if shared_pages is not None and not prefix_pages:
            raise ValueError(
                "shared_pages needs prefix_pages > 0 on the mounting "
                "engine too (the pool shapes come from its own config)")
        if prefix_pages:
            from dtf_tpu.serve import pages as pages_lib

            pool_abs = pages_lib.pool_abstract(
                abs_state["cache"], prefix_pages, kv_page_size, mesh)
            if shared_pages is not None:
                pages_lib.check_pool_compatible(shared_pages.pool, pool_abs)
                if (shared_pages.index.n_pages != prefix_pages
                        or shared_pages.index.page_size != kv_page_size):
                    raise ValueError(
                        f"shared page store is {shared_pages.index.n_pages}"
                        f"x{shared_pages.index.page_size}-token pages; "
                        f"this engine asked for {prefix_pages}"
                        f"x{kv_page_size}")
                self._page_store = shared_pages
                self._owns_pages = False
            else:
                self._page_store = pages_lib.PageStore(
                    _zeros_like_struct(pool_abs),
                    pages_lib.PrefixIndex(prefix_pages, kv_page_size,
                                          save_after=page_save_after))
                self._owns_pages = True
            self.page_trace_counts = {"save": 0, "load": 0}
            page_programs = page_program_table(
                abs_state, pool_abs, n_pages=prefix_pages,
                max_len=max_len, kv_page_size=kv_page_size, mesh=mesh,
                counts=self.page_trace_counts)
            self.programs.update(page_programs)
            self._page_save_c = page_programs["save"].aot()
            self._page_load_c = page_programs["load"].aot()

    # ------------------------------------------------------------- host API

    @property
    def page_store(self):
        """The engine's mountable prefix-page state (None with the cache
        off) — pass as ``shared_pages=`` to further engines to share one
        pool+index (the disaggregation KV transport)."""
        return self._page_store

    @property
    def _prefix(self):
        return None if self._page_store is None else self._page_store.index

    @property
    def _pages(self):
        return self._page_store.pool

    @_pages.setter
    def _pages(self, pool):
        self._page_store.pool = pool

    def n_chunks(self, prompt_len: int) -> int:
        return math.ceil(prompt_len / self.prefill_chunk)

    def _annotation(self, name: str, **ids):
        """A jax.profiler.TraceAnnotation stamping request trace ids into
        the XPlane timeline (``annotate_traces``); a null context
        otherwise. Host-side marker only — never reads a device value."""
        if not self.annotate_traces:
            return contextlib.nullcontext()
        return jax.profiler.TraceAnnotation(name, **ids)

    def prefill_chunk_into(self, slot: int, prompt: Sequence[int],
                           chunk_i: int, *, start: int = 0,
                           temperature: float = 0.0,
                           top_k: int = 0, top_p: float = 1.0,
                           eos_id: Optional[int] = None, pad_id: int = 0,
                           seed: int = 0,
                           trace_id: Optional[int] = None
                           ) -> Optional[tuple[int, bool]]:
        """Run prompt chunk ``chunk_i`` of a request into ``slot`` — the
        scheduler's prefill/decode interleave granularity (decode_all may
        run between chunks; the slot stays a masked spectator until its
        last chunk lands). ``start`` leading tokens are taken as already
        in the slot's cache (prefix pages loaded via
        :meth:`load_prefix_page`) — chunks cover ``prompt[start:]`` only.
        Returns ``(first_token, done)`` on the last chunk, None before."""
        prompt = list(int(t) for t in prompt)
        if not 1 <= len(prompt) <= self.max_len - 1:
            raise ValueError(
                f"prompt length {len(prompt)} must be in [1, "
                f"{self.max_len - 1}] (max_len={self.max_len} covers "
                "prompt + generated tokens)")
        if not 0 <= start < len(prompt):
            raise ValueError(
                f"start={start} must be in [0, {len(prompt)}) — at least "
                "one prompt token must prefill live (the request's first "
                "sampled token comes from the last position's logits)")
        if not 0 <= slot < self.n_slots:
            raise ValueError(f"slot {slot} out of range [0, {self.n_slots})")
        c = self.prefill_chunk
        tail = prompt[start:]
        n = self.n_chunks(len(tail))
        if not 0 <= chunk_i < n:
            raise ValueError(f"chunk {chunk_i} out of range [0, {n})")
        seg = tail[chunk_i * c:(chunk_i + 1) * c]
        buf = np.zeros((c,), np.int32)
        buf[:len(seg)] = seg
        last = chunk_i == n - 1
        with self._annotation("dtf.serve.prefill_chunk", slot=slot,
                              chunk=chunk_i,
                              trace_id=-1 if trace_id is None else trace_id):
            self._state, out = self._prefill_c(
                self._params, self._state, np.int32(slot), np.int32(start),
                buf, np.int32(len(seg)), np.bool_(chunk_i == 0),
                np.bool_(last), np.float32(temperature), np.int32(top_k),
                np.float32(top_p),
                np.int32(-1 if eos_id is None else eos_id),
                np.int32(pad_id),
                np.asarray(jax.random.PRNGKey(seed), np.uint32))
        self.counters["prefill_chunks"] += 1
        if self.spec_k:
            # the DRAFT cache must ingest the same prompt (pages never
            # shortcut it — the draft pool does not exist, and the draft
            # is cheap enough that full-prompt draft prefill still wins):
            # one draft chunk rides along per target chunk, and the tail
            # (page-hit admissions cover fewer live target chunks than
            # the draft's full count) completes with the LAST target
            # chunk, so both models flip active in the same host call.
            if chunk_i == 0:
                self._draft_chunks[slot] = 0
                # a page load just before this admission shortcuts the
                # draft too (self-spec; load_prefix staged the count)
                self._draft_start[slot] = self._draft_pending[slot]
                self._draft_pending[slot] = 0
            dstart = int(self._draft_start[slot])
            n_d = self.n_chunks(len(prompt) - dstart)
            if self._draft_chunks[slot] < n_d:
                self._draft_prefill_chunk(slot, prompt,
                                          int(self._draft_chunks[slot]),
                                          dstart)
            if last:
                while self._draft_chunks[slot] < n_d:
                    self._draft_prefill_chunk(
                        slot, prompt, int(self._draft_chunks[slot]),
                        dstart)
        if not last:
            return None
        if self.spec_k:
            self._spec_index[slot] = len(prompt)
            self._spec_tok[slot] = int(out["token"])
        return int(out["token"]), bool(out["done"])

    def _draft_prefill_chunk(self, slot: int, prompt: Sequence[int],
                             chunk_i: int, start: int = 0) -> None:
        """One fixed-width chunk of the DRAFT model's prefill into
        ``slot`` — the draft_prefill program, covering ``prompt[start:]``
        (``start`` > 0 only under self-speculation, where a page hit
        already landed the stem in the draft cache). The sampled first
        token is discarded: the request's sampling stream belongs to the
        verifier alone."""
        c = self.prefill_chunk
        tail = list(int(t) for t in prompt)[start:]
        n_d = self.n_chunks(len(tail))
        seg = tail[chunk_i * c:(chunk_i + 1) * c]
        buf = np.zeros((c,), np.int32)
        buf[:len(seg)] = seg
        self._draft_state, _ = self._draft_prefill_c(
            self._draft_params, self._draft_state, np.int32(slot),
            np.int32(start), buf, np.int32(len(seg)),
            np.bool_(chunk_i == 0), np.bool_(chunk_i == n_d - 1),
            np.float32(0.0), np.int32(0), np.float32(1.0), np.int32(-1),
            np.int32(0), np.asarray(jax.random.PRNGKey(0), np.uint32))
        self.counters["draft_prefill_chunks"] += 1
        self._draft_chunks[slot] += 1

    def prefill(self, slot: int, prompt: Sequence[int], *, start: int = 0,
                **sampling) -> tuple[int, bool]:
        """Admit a request into ``slot``: stream its whole prompt (minus
        ``start`` page-loaded tokens) through the compiled chunk program
        and sample the first token. Returns ``(first_token, done)``."""
        n = self.n_chunks(len(prompt) - start)
        if n == 0:
            # the per-chunk validation never runs on an empty prompt —
            # fail here, not with a None return at the caller's unpack
            raise ValueError(
                f"prompt length 0 must be in [1, {self.max_len - 1}]")
        out = None
        for i in range(n):
            out = self.prefill_chunk_into(slot, prompt, i, start=start,
                                          **sampling)
        return out

    def decode(self, *, trace_ids: Optional[Sequence[int]] = None):
        """One masked token step across all slots.

        Without a draft model: ``(tokens [n_slots], done [n_slots])`` as
        host arrays — the one device→host sync per generated token (EOS
        and delivery decisions live on the host). With ``spec_k > 0`` the
        step is SPECULATIVE — draft_all proposes k tokens per slot, the
        verify program scores all k+1 positions in one pass — and the
        return is ``(tokens [n_slots, k+1], done [n_slots, k+1],
        n_emit [n_slots])``: the scheduler delivers ``tokens[s, :n_emit
        [s]]`` per slot (still one sync per TICK, now worth up to k+1
        tokens). ``trace_ids`` (scheduler-threaded) names the requests
        this step serves in the XPlane annotation."""
        if self.spec_k:
            return self._decode_spec(trace_ids)
        with self._annotation(
                "dtf.serve.decode",
                trace_ids="" if trace_ids is None
                else ",".join(map(str, trace_ids))):
            self._state, out = self._decode_c(self._params, self._state)
        self.counters["decode_steps"] += 1
        return np.asarray(out["token"]), np.asarray(out["done"])

    def draft_propose(self):
        """One draft_all dispatch: k greedy proposals per slot off the
        draft model's own cache (rolled to the verifier's accepted
        boundary via the host-mirrored sync index first). Split out of
        :meth:`decode` so chaos injectors can wrap it — a poisoned draft
        must fall back to plain decode, not error the request."""
        self._draft_state, props = self._draft_c(
            self._draft_params, self._draft_state, self._spec_tok,
            self._spec_index)
        self.counters["draft_steps"] += 1
        return props

    def _decode_spec(self, trace_ids):
        try:
            props = self.draft_propose()
        except Exception as e:  # noqa: BLE001 — a draft failure must not
            # fail requests: the verify step is CORRECT for arbitrary
            # proposals (worst case it emits 1 token — plain decode), so
            # null proposals are the fallback, not an error.
            log.warning("draft_all failed (%r); falling back to plain "
                        "decode this tick", e)
            self.counters["draft_fallbacks"] += 1
            props = np.zeros((self.n_slots, self.spec_k), np.int32)
        with self._annotation(
                "dtf.serve.decode",
                trace_ids="" if trace_ids is None
                else ",".join(map(str, trace_ids))):
            self._state, out = self._decode_c(self._params, self._state,
                                              props)
        self.counters["decode_steps"] += 1
        toks = np.asarray(out["tokens"])
        dones = np.asarray(out["done"])
        n_emit = np.asarray(out["n_emit"]).astype(np.int32)
        # host mirrors advance from values this readback carries anyway
        live = n_emit > 0
        self._spec_index = self._spec_index + n_emit
        picked = toks[np.arange(self.n_slots), np.maximum(n_emit, 1) - 1]
        self._spec_tok = np.where(live, picked,
                                  self._spec_tok).astype(np.int32)
        self.counters["spec_proposed"] += int(self.spec_k * live.sum())
        self.counters["spec_accepted"] += int((n_emit[live] - 1).sum())
        return toks, dones, n_emit

    def probe(self) -> None:
        """One decode dispatch with the outputs discarded — the Router's
        PROBATION health probe: a re-admitted replica proves the engine
        answers at normal latency before live traffic gambles on it.
        Deliberately routes through :meth:`decode` (NOT the raw compiled
        executable): anything wrapping the instance's ``decode`` — the
        serve fault injectors, a future engine proxy — must be observed
        by the probe, or a still-wedged replica would probe clean and be
        re-admitted into an oscillation. Same compiled ``decode_all``
        program (no retrace — ``trace_counts`` stays pinned); stale slots
        advance like any other masked step, which is safe by the PR 4
        reset contract: an admitted request fully reinitializes its slot,
        so probes can never perturb request tokens."""
        self.decode()
        self.counters["probe_decodes"] += 1

    # -------------------------------------------------- weight hot-swap

    @staticmethod
    def _check_tree_like(new, old, what: str) -> None:
        """New weights must be drop-in for the compiled executables:
        same tree, same shapes, same dtypes — anything else would need a
        recompile, which hot-swap exists to avoid. Fails loudly naming
        the first offending leaf."""
        nf, ntd = jax.tree_util.tree_flatten_with_path(new)
        of, otd = jax.tree_util.tree_flatten_with_path(old)
        if ntd != otd:
            raise ValueError(
                f"swap_params: new {what} tree structure differs from "
                "the served tree — hot-swap needs the SAME architecture "
                "(a different config is a new engine, not a swap)")
        for (pn, n), (_, o) in zip(nf, of):
            if (tuple(n.shape) != tuple(o.shape)
                    or np.dtype(n.dtype) != np.dtype(o.dtype)):
                raise ValueError(
                    f"swap_params: {what} leaf "
                    f"{jax.tree_util.keystr(pn)} is {tuple(n.shape)}/"
                    f"{np.dtype(n.dtype)}, the served engine expects "
                    f"{tuple(o.shape)}/{np.dtype(o.dtype)}")

    def set_param_version(self, version: int) -> None:
        """Stamp the version of the weights this engine was BUILT with
        (serving a published version from startup) — no swap, no
        counters; call before any traffic so record stamps and page
        epochs carry the real version instead of 0."""
        self.param_version = int(version)

    def swap_params(self, params: PyTree, *, draft_params: PyTree = None,
                    version: Optional[int] = None) -> int:
        """Hot-swap the served weights in place — ZERO recompiles.

        The new tree is validated against the served one (same
        structure/shapes/dtypes, :meth:`_check_tree_like`) and re-placed
        onto the OLD leaves' shardings (``jax.device_put`` per leaf —
        single device and TP mesh alike), so the AOT executables accept
        the new arrays exactly like the old ones: ``trace_counts`` stays
        pinned (counter-tested in tests/test_serve_swap.py).

        Caller contract (the Router's rolling swap enforces it): the
        engine must be DRAINED — no queued/admitting/running request —
        when this runs; an in-flight stream would otherwise mix logits
        of two versions. Stale slot state needs no cleanup (the PR 4
        reset contract: an admitted request fully reinitializes its
        slot), and the prefix-page EPOCH bump makes every page the old
        weights produced unreachable from this engine.

        For a SPEC engine the draft rides the same transaction:
        ``draft_params`` swaps it explicitly; under SELF-speculation the
        new target tree is the draft by definition; a distinct draft
        with no new weights keeps proposing from the old ones — still
        correct (the verifier samples every delivered token; proposals
        only set the acceptance rate), just logged.

        ``version`` stamps :attr:`param_version` (the publish version);
        default is the previous version + 1. Returns the new version."""
        self._check_tree_like(params, self._params, "params")
        # re-place onto the OLD leaves' shardings: the committed layout
        # the AOT executables were compiled against, whatever devices/
        # mesh that is — a host array, a differently-placed array or a
        # resharded tree all land right
        placed = jax.tree.map(
            lambda n, o: jax.device_put(n, o.sharding),
            params, self._params)
        placed_draft = None
        if self.spec_k:
            if draft_params is not None:
                self._check_tree_like(draft_params, self._draft_params,
                                      "draft_params")
                placed_draft = jax.tree.map(
                    lambda n, o: jax.device_put(n, o.sharding),
                    draft_params, self._draft_params)
            elif self._draft_self:
                # self-speculation: draft ≡ target architecture AND
                # weights — the one placed tree swaps both sides
                placed_draft = placed
            else:
                log.info(
                    "swap_params: spec engine keeps its previous draft "
                    "weights (no draft_params passed for a distinct "
                    "draft model) — acceptance may drop, correctness "
                    "cannot (the verifier owns the token stream)")
        # THE transaction: target, draft and version flip together,
        # between compiled dispatches (the pump loop is single-threaded)
        self._params = placed
        if placed_draft is not None:
            self._draft_params = placed_draft
        self.param_version = (int(version) if version is not None
                              else self.param_version + 1)
        self.counters["param_swaps"] += 1
        return self.param_version

    # ----------------------------------------------------- prefix page API

    def prefix_match(self, prompt: Sequence[int]):
        """Admission-time lookup: the longest cached page chain exactly
        matching a prefix of ``prompt`` AT THIS ENGINE's param version
        (pages are epoch-keyed — KV from other weight versions is
        unreachable), PINNED until :meth:`release_prefix` (the scheduler
        releases on slot evict). None on a miss or with the page cache
        off."""
        if self._prefix is None:
            return None
        prompt = tuple(int(t) for t in prompt)
        h = self._prefix.acquire(prompt, epoch=self.param_version)
        if h is None:
            self.counters["prefix_miss_tokens"] += len(prompt)
        else:
            self.counters["prefix_hit_tokens"] += h.n_tokens
            self.counters["prefix_miss_tokens"] += len(prompt) - h.n_tokens
        return h

    def _ids_buf(self, ids: Sequence[int]) -> np.ndarray:
        buf = np.zeros((self.max_len // self.page_size,), np.int32)
        buf[:len(ids)] = ids
        return buf

    def load_prefix(self, slot: int, handle) -> None:
        """Gather a pinned chain's pages into ``slot``'s leading cache
        positions — ONE compiled dispatch for the whole chain, replacing
        ``n_tokens/prefill_chunk`` transformer chunks of prefill work (the
        saving the page cache exists for; a per-page spelling would give
        most of it back as host dispatch overhead)."""
        ids = [e.page_id for e in handle.entries]
        self._state = self._page_load_c(
            self._state, self._pages, np.int32(slot), self._ids_buf(ids),
            np.int32(len(ids)))
        self.counters["pages_loaded"] += len(ids)
        if self.spec_k and self._draft_self:
            # self-speculation: the draft cache is struct-identical, so
            # the SAME compiled gather lands the chain there too — the
            # draft's prefill then covers only the uncached tail, like
            # the target's (no draft page programs exist or are needed)
            self._draft_state = self._page_load_c(
                self._draft_state, self._pages, np.int32(slot),
                self._ids_buf(ids), np.int32(len(ids)))
            self._draft_pending[slot] = handle.n_tokens
            self.counters["draft_pages_loaded"] += len(ids)

    def save_prefix_pages(self, slot: int, prompt: Sequence[int]) -> None:
        """After a request's LAST prefill chunk: register every full page
        of its prompt not yet in the pool and scatter them out of the
        slot's freshly written KV — one dispatch however many pages are
        new. Stops silently when the pool is exhausted by pinned/parented
        pages — saving is an optimization, never a blocker."""
        if self._prefix is None:
            return
        prompt = tuple(int(t) for t in prompt)
        epoch = self.param_version
        full = len(prompt) // self.page_size
        have, parent = self._prefix.longest(prompt, cap=full, epoch=epoch)
        # save admission: only prefixes traffic has repeated are worth a
        # dispatch — a unique tail page would cost host overhead and a
        # pool slot for KV nobody will ever hit (pages.py docstring)
        full = have + self._prefix.save_eligible(prompt, have, full,
                                                 epoch=epoch)
        ids = []
        for i in range(have, full):
            ent = self._prefix.reserve(prompt[:(i + 1) * self.page_size],
                                       parent, epoch=epoch)
            if ent is None:
                break
            ids.append(ent.page_id)
            parent = ent
        if not ids:
            return
        buf = self._ids_buf([0] * have + ids)
        self._pages = self._page_save_c(
            self._state, self._pages, np.int32(slot), buf, np.int32(have),
            np.int32(have + len(ids)))
        self.counters["pages_saved"] += len(ids)

    def release_prefix(self, handle) -> None:
        """Unpin an admission chain (call exactly once, on slot evict)."""
        if handle is not None:
            self._prefix.release(handle)

    def warm_page_programs(self) -> None:
        """Run both page programs once with no-op operands (n_valid=0
        load, empty [lo, hi) save window) so first-call backend overhead
        lands outside any timed window — the bench A/B warms every
        program before its measured section, and this keeps the calling
        convention next to the programs it warms instead of spelled out
        in the bench. No cache row or pool page changes. No-op with the
        cache off."""
        if self._prefix is None:
            return
        buf = self._ids_buf([])
        self._state = self._page_load_c(self._state, self._pages,
                                        np.int32(0), buf, np.int32(0))
        self._pages = self._page_save_c(self._state, self._pages,
                                        np.int32(0), buf, np.int32(0),
                                        np.int32(0))

    def prefix_stats(self) -> dict:
        """Page-cache aggregates (empty dict with the cache off)."""
        if self._prefix is None:
            return {}
        return {**self._prefix.stats,
                "pages": self.n_pages - self._prefix.n_free,
                "pages_free": self._prefix.n_free,
                # live pins should drain to 0 once every admitted request
                # released its handle — a leak here is a requeue/evict
                # path dropping the pages.py refcount contract
                "pinned": self._prefix.pinned()}

    def cache_bytes(self) -> int:
        """Resident KV footprint: slot cache + page pool (a MOUNTED shared
        pool counts on its owning engine only — summing a fleet must not
        multiply one pool by the replica count), all layers; with a draft
        model, its slot cache too."""
        leaves = jax.tree.leaves(self._state["cache"])
        if self._prefix is not None and self._owns_pages:
            leaves += jax.tree.leaves(self._pages)
        if self.spec_k:
            leaves += jax.tree.leaves(self._draft_state["cache"])
        return sum(int(np.prod(x.shape)) * x.dtype.itemsize
                   for x in leaves)


def engine_state_struct(cfg: gpt.GPTConfig, *, n_slots: int, max_len: int,
                        mesh: Optional[Mesh] = None) -> PyTree:
    """Abstract engine state (slot-batched KV cache + per-slot arrays)
    exactly as a ``DecodeEngine(cfg, n_slots=, max_len=)`` would allocate
    it — ShapeDtypeStructs with the engine's shardings attached.  The
    introspection hook the HBM fit planner (``python -m dtf_tpu.analysis
    fit``) prices per-slot KV bytes from (bf16 vs int8 via
    ``cfg.kv_cache_dtype``), and the page-pool twin of
    :func:`dtf_tpu.serve.pages.pool_abstract` — eval_shape only, no
    device memory, no compile."""
    dec = dataclasses.replace(cfg, decode_len=max_len, slot_decode=True,
                              chunked_prefill=False)
    return _state_struct(dec, n_slots, mesh)


#: the prefill program's operand names (state + scalar tail) in
#: positional order — the bundling key the analysis views use to turn a
#: program_table entry's abstract_args into the runner's two-argument
#: (params, ops) step shape.
_PREFILL_OPS = ("state", "slot", "start", "chunk", "n_valid", "reset",
                "is_last", "temp", "top_k", "top_p", "eos", "pad", "key")


def program_table(cfg: gpt.GPTConfig, *, n_slots: int, max_len: int,
                  mesh: Optional[Mesh] = None, prefill_chunk: int = 8,
                  spec_k: int = 0,
                  draft_cfg: Optional[gpt.GPTConfig] = None,
                  counts: Optional[dict] = None,
                  abs_trees: Optional[dict] = None):
    """Build the serve tier's core programs as fenced executor Programs.

    THE one construction (ISSUE 18): ``DecodeEngine.__init__`` AOT-
    compiles exactly this table (passing ``abs_trees`` derived from its
    real arrays so restored-checkpoint shardings are honored), and the
    analysis step views below enumerate the same table built from rule-
    derived abstract trees — the fenced graph and the served graph are
    the same construction, not hand-kept twins.

    Returns ``(programs, models)``: ``programs`` maps ``decode`` (the
    verify program when ``spec_k > 0`` — verify IS spec decode),
    ``prefill``, and with a draft ``draft_prefill`` + ``draft``, to
    :class:`dtf_tpu.core.executor.Program`s with their operand abstracts
    registered; ``models`` the matching flax modules. ``counts`` is the
    shared trace fence dict (``DecodeEngine.trace_counts``). ``probe()``
    needs no entry: it replays the compiled decode program.
    """
    base = dataclasses.replace(cfg, decode_len=max_len, slot_decode=False,
                               chunked_prefill=False)
    dec_cfg = dataclasses.replace(base, slot_decode=True)
    abs_trees = dict(abs_trees or {})
    abs_params = abs_trees.get("params")
    if abs_params is None:
        abs_params = _abs_params(base, mesh)
    abs_state = abs_trees.get("state")
    if abs_state is None:
        abs_state = _state_struct(dec_cfg, n_slots, mesh)
    models = {
        "decode": gpt.GPT(dec_cfg, mesh),
        "prefill": gpt.GPT(
            dataclasses.replace(base, chunked_prefill=True), mesh),
    }
    s_i32 = jax.ShapeDtypeStruct((), jnp.int32)
    s_f32 = jax.ShapeDtypeStruct((), jnp.float32)
    s_bool = jax.ShapeDtypeStruct((), jnp.bool_)
    chunk_abs = jax.ShapeDtypeStruct((prefill_chunk,), jnp.int32)
    key_abs = jax.ShapeDtypeStruct((2,), jnp.uint32)
    #: prefill_into_slot's scalar operand tail, shared by both prefill
    #: programs (and re-bundled by prefill_step_view/disagg_step_view).
    prefill_tail = (s_i32, s_i32, chunk_abs, s_i32, s_bool, s_bool,
                    s_f32, s_i32, s_f32, s_i32, s_i32, key_abs)
    jit_kw, verify_kw = {}, {}
    rep = None
    if mesh is not None:
        # pin the OUTPUT state to the input layout: GSPMD would otherwise
        # pick its own output shardings, and the next call of the AOT
        # executable would reject the resharded state
        rep = NamedSharding(mesh, P())
        state_sh = jax.tree.map(lambda s: s.sharding, abs_state)
        jit_kw["out_shardings"] = (state_sh, {"token": rep, "done": rep})
        verify_kw["out_shardings"] = (state_sh,
                                      {"tokens": rep, "done": rep,
                                       "n_emit": rep})
    programs = {}
    if spec_k:
        props_abs = jax.ShapeDtypeStruct((n_slots, spec_k), jnp.int32,
                                         sharding=rep)
        executor.program(
            "decode", _build_verify_fn(models["decode"], spec_k),
            counts=counts, jit_kw=verify_kw,
            abstract_args=(abs_params, abs_state, props_abs),
            table=programs)
    else:
        executor.program(
            "decode", _build_decode_fn(models["decode"]),
            counts=counts, jit_kw=jit_kw,
            abstract_args=(abs_params, abs_state), table=programs)
    executor.program(
        "prefill", _build_prefill_fn(models["prefill"]),
        counts=counts, jit_kw=jit_kw,
        abstract_args=(abs_params, abs_state) + prefill_tail,
        table=programs)
    if spec_k:
        dbase = dataclasses.replace(draft_cfg, decode_len=max_len,
                                    slot_decode=False, chunked_prefill=False)
        ddec_cfg = dataclasses.replace(dbase, slot_decode=True)
        models["draft"] = gpt.GPT(ddec_cfg, mesh)
        models["draft_prefill"] = gpt.GPT(
            dataclasses.replace(dbase, chunked_prefill=True), mesh)
        abs_dparams = abs_trees.get("draft_params")
        if abs_dparams is None:
            abs_dparams = _abs_params(dbase, mesh)
        abs_dstate = abs_trees.get("draft_state")
        if abs_dstate is None:
            abs_dstate = _state_struct(ddec_cfg, n_slots, mesh)
        dp_kw, da_kw = {}, {}
        if mesh is not None:
            dstate_sh = jax.tree.map(lambda s: s.sharding, abs_dstate)
            dp_kw["out_shardings"] = (dstate_sh,
                                      {"token": rep, "done": rep})
            da_kw["out_shardings"] = (dstate_sh, rep)
        vec_abs = jax.ShapeDtypeStruct((n_slots,), jnp.int32, sharding=rep)
        executor.program(
            "draft_prefill", _build_prefill_fn(models["draft_prefill"]),
            counts=counts, jit_kw=dp_kw,
            abstract_args=(abs_dparams, abs_dstate) + prefill_tail,
            table=programs)
        executor.program(
            "draft", _build_draft_fn(models["draft"], spec_k),
            counts=counts, jit_kw=da_kw,
            abstract_args=(abs_dparams, abs_dstate, vec_abs, vec_abs),
            table=programs)
    return programs, models


def page_program_table(abs_state: PyTree, pool_abs: PyTree, *,
                       n_pages: int, max_len: int, kv_page_size: int,
                       mesh: Optional[Mesh] = None,
                       counts: Optional[dict] = None):
    """The two page programs (``save``/``load``) as fenced Programs —
    same shared-construction contract as :func:`program_table`, split out
    because the page pool is optional (``prefix_pages > 0``) and carries
    its own trace fence (``DecodeEngine.page_trace_counts``)."""
    save_kw, load_kw = {}, {}
    if mesh is not None:
        # same pin rationale as program_table: the AOT executables must
        # keep the pool/state in their committed layouts
        save_kw["out_shardings"] = jax.tree.map(
            lambda s: s.sharding, pool_abs)
        load_kw["out_shardings"] = jax.tree.map(
            lambda s: s.sharding, abs_state)
    s_i32 = jax.ShapeDtypeStruct((), jnp.int32)
    ids_abs = jax.ShapeDtypeStruct((max_len // kv_page_size,), jnp.int32)
    programs = {}
    executor.program(
        "save", _build_page_save_fn(n_pages), counts=counts,
        jit_kw=save_kw,
        abstract_args=(abs_state, pool_abs, s_i32, ids_abs, s_i32, s_i32),
        table=programs)
    executor.program(
        "load", _build_page_load_fn(), counts=counts, jit_kw=load_kw,
        abstract_args=(abs_state, pool_abs, s_i32, ids_abs, s_i32),
        table=programs)
    return programs


def decode_step_view(cfg: gpt.GPTConfig, *, n_slots: int, max_len: int,
                     mesh: Optional[Mesh] = None):
    """The engine's decode program as an analyzable step:
    ``(program, abstract_params, abstract_state)`` — the ``decode``
    entry of :func:`program_table`, so the comms-budget fence covers the
    serving decode graph exactly as ``DecodeEngine`` compiles it (same
    model, same state layout, same shardings, same construction)."""
    programs, _ = program_table(cfg, n_slots=n_slots, max_len=max_len,
                                mesh=mesh)
    prog = programs["decode"]
    abs_params, abs_state = prog.abstract_args
    # the fenced view is the table's body WITHOUT the engine's output
    # pins: the pin exists for AOT reuse (reject resharded state), but it
    # costs extra replication all-gathers the served per-tick graph never
    # runs (the engine feeds each output straight back in) — pinning here
    # would charge the comms budget for transfers that don't happen.
    view = executor.program("decode_view", prog.body,
                            abstract_args=(abs_params, abs_state))
    return view, abs_params, abs_state


def _abs_params(cfg: gpt.GPTConfig, mesh: Optional[Mesh]) -> PyTree:
    """Abstract TP-sharded param tree — identical across the decode /
    prefill / page model variants (architecture config, not cache mode)."""
    from dtf_tpu.core.sharding import tree_shardings

    model = gpt.GPT(dataclasses.replace(cfg, slot_decode=True), mesh)
    shapes = jax.eval_shape(lambda: model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 1), jnp.int32)))
    abs_params = shapes["params"]
    if mesh is not None:
        abs_params = jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                               sharding=sh),
            abs_params, tree_shardings(abs_params, mesh, gpt.tp_rules))
    return abs_params


def prefill_step_view(cfg: gpt.GPTConfig, *, n_slots: int, max_len: int,
                      prefill_chunk: int = 8, mesh: Optional[Mesh] = None):
    """The engine's prefill program as an analyzable step:
    ``(jitted_fn, abstract_params, abstract_operand_bundle)`` — the same
    ``prefill_into_slot`` body ``DecodeEngine`` AOT-compiles (slot slice →
    chunked-prefill model → slot write-back → first-token sample), with
    the scalar operands bundled into one pytree so the analysis runner's
    two-argument step shape fits. The comms-budget fence this enables
    covers the known sharded-prefill resharding cost (engine docstring:
    GSPMD respells the traced-index slot slice as a resharding of the
    touched cache leaves) — previously documented, now pinned."""
    programs, _ = program_table(cfg, n_slots=n_slots, max_len=max_len,
                                mesh=mesh, prefill_chunk=prefill_chunk)
    prog = programs["prefill"]
    abs_params, abs_state = prog.abstract_args[:2]
    ops = dict(zip(_PREFILL_OPS, prog.abstract_args[1:]))

    def step(params, ops):
        return prog.body(
            params, ops["state"], ops["slot"], ops["start"], ops["chunk"],
            ops["n_valid"], ops["reset"], ops["is_last"], ops["temp"],
            ops["top_k"], ops["top_p"], ops["eos"], ops["pad"], ops["key"])

    jit_kw = {}
    if mesh is not None:
        # the engine pins the output state to the input layout (its AOT
        # executables reject resharded state) — the fenced graph must be
        # the SAME pinned program, not GSPMD's free choice
        rep = NamedSharding(mesh, P())
        jit_kw["out_shardings"] = (
            jax.tree.map(lambda s: s.sharding, abs_state),
            {"token": rep, "done": rep})
    return (executor.program("prefill_view", step, jit_kw=jit_kw,
                             abstract_args=(abs_params, ops)),
            abs_params, ops)


def page_step_view(cfg: gpt.GPTConfig, *, n_slots: int, max_len: int,
                   kv_page_size: int, n_pages: int,
                   mesh: Optional[Mesh] = None):
    """The page programs as one analyzable step: ``page_load`` of a
    pinned chain followed by ``page_save`` of the new pages — an
    admission tick, exactly the two extra AOT programs a
    ``prefix_pages > 0`` engine compiles (their own trace fence,
    ``page_trace_counts``). Returned as ``(jitted_fn, state_bundle,
    operand_bundle)``; the fence pins the batched gather/scatter
    collectives so a pool-layout change that makes GSPMD move whole
    cache leaves per admission fails tier-1 first."""
    from dtf_tpu.serve import pages as pages_lib

    if max_len % kv_page_size:
        raise ValueError(
            f"kv_page_size={kv_page_size} does not divide "
            f"max_len={max_len} (same rule as DecodeEngine)")
    dec_cfg = dataclasses.replace(cfg, decode_len=max_len, slot_decode=True)
    state_abs = _state_struct(dec_cfg, n_slots, mesh)
    pool_abs = pages_lib.pool_abstract(state_abs["cache"], n_pages,
                                       kv_page_size, mesh)
    pages = page_program_table(state_abs, pool_abs, n_pages=n_pages,
                               max_len=max_len, kv_page_size=kv_page_size,
                               mesh=mesh)
    load_fn = pages["load"].body
    save_fn = pages["save"].body

    def step(bundle, ops):
        st = load_fn(bundle["state"], bundle["pool"], ops["slot"],
                     ops["ids"], ops["n_valid"])
        pool = save_fn(st, bundle["pool"], ops["slot"], ops["ids"],
                       ops["lo"], ops["hi"])
        return {"state": st, "pool": pool}

    jit_kw = {}
    if mesh is not None:
        # same pin as the engine's page programs (load_kw/save_kw): the
        # fence must compile the pinned layouts, not GSPMD's free choice
        jit_kw["out_shardings"] = {
            "state": jax.tree.map(lambda s: s.sharding, state_abs),
            "pool": jax.tree.map(lambda s: s.sharding, pool_abs)}
    s_i32 = jax.ShapeDtypeStruct((), jnp.int32)
    ops = {"slot": s_i32,
           "ids": jax.ShapeDtypeStruct((max_len // kv_page_size,),
                                       jnp.int32),
           "n_valid": s_i32, "lo": s_i32, "hi": s_i32}
    bundle = {"state": state_abs, "pool": pool_abs}
    return (executor.program("page_view", step, jit_kw=jit_kw,
                             abstract_args=(bundle, ops)),
            bundle, ops)


def spec_step_view(cfg: gpt.GPTConfig, draft_cfg: gpt.GPTConfig, *,
                   n_slots: int, max_len: int, spec_k: int,
                   mesh: Optional[Mesh] = None):
    """The SPECULATIVE tick (``draft_all`` ∘ ``verify``) as one
    analyzable step — the two extra graphs a spec engine compiles, fenced
    together the way ``page_step_view`` fences an admission tick. The
    comms budget pins both the draft's unrolled k-step loop and the
    (k+1)-wide verify pass (its TP all-reduces, the per-row cache
    scatter, the rollback assignment); the memory fence prices the
    k-token verify temp and the draft's resident cache — the numbers
    ``analysis fit`` needs to answer "max slots with spec on"."""
    programs, _ = program_table(cfg, n_slots=n_slots, max_len=max_len,
                                mesh=mesh, spec_k=spec_k,
                                draft_cfg=draft_cfg)
    verify_fn = programs["decode"].body
    draft_fn = programs["draft"].body

    def step(bundle, ops):
        dstate, props = draft_fn(bundle["draft_params"],
                                 bundle["draft_state"],
                                 ops["tok"], ops["sync_index"])
        state, out = verify_fn(bundle["params"], bundle["state"], props)
        return {"state": state, "draft_state": dstate, "out": out}

    abs_params, abs_state = programs["decode"].abstract_args[:2]
    abs_dparams, abs_dstate = programs["draft"].abstract_args[:2]
    bundle = {"params": abs_params, "draft_params": abs_dparams,
              "state": abs_state, "draft_state": abs_dstate}
    vec = jax.ShapeDtypeStruct((n_slots,), jnp.int32)
    ops = {"tok": vec, "sync_index": vec}
    jit_kw = {}
    if mesh is not None:
        rep = NamedSharding(mesh, P())
        jit_kw["out_shardings"] = {
            "state": jax.tree.map(lambda s: s.sharding, abs_state),
            "draft_state": jax.tree.map(lambda s: s.sharding, abs_dstate),
            "out": {"tokens": rep, "done": rep, "n_emit": rep}}
    return (executor.program("spec_view", step, jit_kw=jit_kw,
                             abstract_args=(bundle, ops)),
            bundle, ops)


def disagg_step_view(cfg: gpt.GPTConfig, *, n_slots: int, max_len: int,
                     prefill_chunk: int, kv_page_size: int, n_pages: int,
                     mesh: Optional[Mesh] = None):
    """The PREFILL-replica admission tick of a disaggregated fleet
    (``prefill_into_slot`` ∘ ``page_save``): the handoff-producing
    composition — a dedicated prefill replica's whole job is to run
    prompt chunks and scatter the resulting KV pages into the shared
    pool for decode replicas to gather. Fencing the composition pins the
    transport's collective structure (the TP projections of the chunk
    plus the pool scatter over data shards) so a layout change that
    turns the handoff into whole-leaf traffic fails tier-1 first."""
    if max_len % kv_page_size:
        raise ValueError(
            f"kv_page_size={kv_page_size} does not divide "
            f"max_len={max_len} (same rule as DecodeEngine)")
    base = dataclasses.replace(cfg, decode_len=max_len, slot_decode=False,
                               chunked_prefill=False)
    programs, _ = program_table(cfg, n_slots=n_slots, max_len=max_len,
                                mesh=mesh, prefill_chunk=prefill_chunk)
    prefill_fn = programs["prefill"].body
    state_abs = _state_struct(
        dataclasses.replace(base, slot_decode=True), n_slots, mesh)
    from dtf_tpu.serve import pages as pages_lib

    pool_abs = pages_lib.pool_abstract(state_abs["cache"], n_pages,
                                       kv_page_size, mesh)
    pages = page_program_table(state_abs, pool_abs, n_pages=n_pages,
                               max_len=max_len, kv_page_size=kv_page_size,
                               mesh=mesh)
    save_fn = pages["save"].body

    def step(bundle, ops):
        state, out = prefill_fn(
            bundle["params"], bundle["state"], ops["slot"], ops["start"],
            ops["chunk"], ops["n_valid"], ops["reset"], ops["is_last"],
            ops["temp"], ops["top_k"], ops["top_p"], ops["eos"],
            ops["pad"], ops["key"])
        pool = save_fn(state, bundle["pool"], ops["slot"], ops["ids"],
                       ops["lo"], ops["hi"])
        return {"state": state, "pool": pool, "out": out}

    jit_kw = {}
    if mesh is not None:
        rep = NamedSharding(mesh, P())
        jit_kw["out_shardings"] = {
            "state": jax.tree.map(lambda s: s.sharding, state_abs),
            "pool": jax.tree.map(lambda s: s.sharding, pool_abs),
            "out": {"token": rep, "done": rep}}
    s_i32 = jax.ShapeDtypeStruct((), jnp.int32)
    ops = {
        "slot": s_i32, "start": s_i32,
        "chunk": jax.ShapeDtypeStruct((prefill_chunk,), jnp.int32),
        "n_valid": s_i32,
        "reset": jax.ShapeDtypeStruct((), jnp.bool_),
        "is_last": jax.ShapeDtypeStruct((), jnp.bool_),
        "temp": jax.ShapeDtypeStruct((), jnp.float32),
        "top_k": s_i32,
        "top_p": jax.ShapeDtypeStruct((), jnp.float32),
        "eos": s_i32, "pad": s_i32,
        "key": jax.ShapeDtypeStruct((2,), jnp.uint32),
        "ids": jax.ShapeDtypeStruct((max_len // kv_page_size,), jnp.int32),
        "lo": s_i32, "hi": s_i32,
    }
    bundle = {"params": _abs_params(base, mesh), "state": state_abs,
              "pool": pool_abs}
    return (executor.program("disagg_view", step, jit_kw=jit_kw,
                             abstract_args=(bundle, ops)),
            bundle, ops)
