"""Multi-replica router — the serving tier above :class:`DecodeEngine`.

One engine is one KV-cache pool on one device set; the ROADMAP's
millions-of-users north star needs N of them behind one front door. A
:class:`Router` owns N ``(DecodeEngine, Scheduler)`` replicas that SHARE
one restored param tree (weights are read-only at serve time — N replicas
cost N KV caches, not N param copies) while keeping fully independent KV
state, and admits each request to the replica with the **least slot
occupancy**, breaking ties by **queue depth** (then replica index, for
determinism). Every replica keeps the engine's fixed-shape discipline:
``trace_counts`` stays ``{prefill: 1, decode: 1}`` per replica and the
``gpt_serve`` comms fence covers each replica's decode graph identically.

Observability is the PR 5 span surface, serving edition:

- ``router_wait`` — queue time between submit and a replica accepting the
  request into a slot (recorded by the scheduler at admission; host
  clocks only, zero added device readbacks);
- per-replica TTFT/occupancy/SLO rollups in :meth:`Router.stats`
  (``replica{i}_*`` keys) next to the fleet aggregates — ``ttft_slo_s``
  sets the TTFT objective each replica reports compliance against.

The router is drop-in for the scheduler in the pump loop: it exposes the
same ``submit/tick/pending`` surface, so :func:`dtf_tpu.serve.client.replay`
drives a fleet exactly like a single scheduler (the bench A/B rides this).
"""

from __future__ import annotations

import time
from typing import Sequence

from dtf_tpu.metrics import quantile as _quantile
from dtf_tpu.serve.engine import DecodeEngine
from dtf_tpu.serve.scheduler import Request, Scheduler

#: per-replica stat keys surfaced as ``replica{i}_<key>`` (the SLO panel);
#: everything else stays per-scheduler to keep the JSON line bounded.
_REPLICA_KEYS = ("serve_completed", "serve_occupancy_mean",
                 "serve_ttft_p50_s", "serve_ttft_p99_s",
                 "serve_queue_peak", "serve_ttft_slo_ok_frac")


class Router:
    """Least-occupancy admission over N engine replicas (module docstring).

    Build from live engines (params already shared by construction — pass
    the same tree to each) or via :meth:`build`. ``ttft_slo_s``/``clock``/
    scheduler knobs apply to every replica's scheduler uniformly.
    """

    def __init__(self, engines: Sequence[DecodeEngine], writer=None, *,
                 telemetry=None, ttft_slo_s: float = 0.0,
                 clock=time.monotonic, **scheduler_kw):
        if not engines:
            raise ValueError("Router needs at least one engine replica")
        self.telemetry = telemetry
        self.schedulers = [
            Scheduler(e, writer, telemetry=telemetry,
                      ttft_slo_s=ttft_slo_s, clock=clock,
                      postmortem_name=None, **scheduler_kw)
            for e in engines]
        if telemetry is not None:
            # ONE aggregate postmortem provider for the fleet (each
            # replica's provider would collide on the name): in-flight
            # request ids + slot ages per replica, host facts only.
            telemetry.add_postmortem_provider(
                "serve_router", self.postmortem_state)
        self.ttft_slo_s = ttft_slo_s
        self._where: dict[int, tuple[int, int]] = {}
        self._next_id = 0

    @classmethod
    def build(cls, cfg, params, *, n_replicas: int, n_slots: int,
              max_len: int, prefill_chunk: int = 16, mesh=None,
              kv_page_size: int = 0, prefix_pages: int = 0,
              page_save_after: int = 2, **router_kw) -> "Router":
        """N identical replicas over ONE param tree. Each replica gets its
        own KV state (and page pool, when enabled) and its own pair of AOT
        programs; the params device arrays are shared."""
        if n_replicas < 1:
            raise ValueError(f"n_replicas={n_replicas} must be >= 1")
        engines = [DecodeEngine(cfg, params, n_slots=n_slots,
                                max_len=max_len,
                                prefill_chunk=prefill_chunk, mesh=mesh,
                                kv_page_size=kv_page_size,
                                prefix_pages=prefix_pages,
                                page_save_after=page_save_after)
                   for _ in range(n_replicas)]
        return cls(engines, **router_kw)

    # ------------------------------------------------------------ admission

    def _pick(self) -> int:
        """Least occupancy; queue depth breaks the tie (every replica
        saturated → the shortest line), replica index breaks that
        (deterministic tests)."""
        return min(range(len(self.schedulers)),
                   key=lambda i: (self.schedulers[i].occupancy,
                                  self.schedulers[i].queue_depth, i))

    def submit(self, req: Request) -> int:
        i = self._pick()
        # the fleet-global rid IS the request's trace id: every span the
        # replica scheduler and engine record for it carries this one id,
        # so a request renders end-to-end across the tiers in Perfetto.
        # Increment only after the replica ACCEPTED — a rejected submit
        # (over-long prompt) must not consume a fleet id.
        rid = self._next_id
        local = self.schedulers[i].submit(req, trace_id=rid)
        self._next_id += 1
        self._where[rid] = (i, local)
        return rid

    def replica_of(self, rid: int) -> int:
        """Which replica holds request ``rid`` (admission audit)."""
        return self._where[rid][0]

    def postmortem_state(self) -> dict:
        """Fleet postmortem context: per-replica in-flight request ids and
        slot ages (host facts only — the flight-recorder dump contract)."""
        return {f"replica{i}": s.postmortem_state()
                for i, s in enumerate(self.schedulers)}

    # ----------------------------------------------------------- pump surface

    @property
    def pending(self) -> int:
        return sum(s.pending for s in self.schedulers)

    def tick(self) -> None:
        """One scheduling round on every replica with work — replicas are
        independent KV state, so their ticks never contend for slots."""
        for s in self.schedulers:
            if s.pending:
                s.tick()

    def run_until_idle(self, max_ticks: int = 100000, *,
                       on_tick=None) -> None:
        for _ in range(max_ticks):
            if not self.pending:
                return
            self.tick()
            if on_tick is not None:
                on_tick()
        raise RuntimeError(f"requests still pending after {max_ticks} ticks")

    def poll(self, rid: int) -> dict:
        i, local = self._where[rid]
        return self.schedulers[i].poll(local)

    def result(self, rid: int, max_ticks: int = 100000) -> list[int]:
        for _ in range(max_ticks):
            st = self.poll(rid)
            if st["status"] == "done":
                return st["tokens"]
            self.tick()
        raise RuntimeError(f"request {rid} not done after {max_ticks} ticks")

    def release(self, rid: int) -> None:
        i, local = self._where.pop(rid)
        self.schedulers[i].release(local)

    def drain(self) -> None:
        self.run_until_idle()

    # --------------------------------------------------------------- metrics

    def trace_counts(self) -> list[dict]:
        """Per-replica program trace counters (page fences merged in) —
        the steady-state recompile pin, fleet edition."""
        return [{**s.engine.trace_counts,
                 **{f"page_{k}": v
                    for k, v in s.engine.page_trace_counts.items()}}
                for s in self.schedulers]

    def stats(self, brief: bool = False) -> dict:
        """Fleet aggregates + the ``replica{i}_*`` SLO panel."""
        n = len(self.schedulers)
        out = {
            "router_replicas": float(n),
            "router_completed": float(sum(s._completed
                                          for s in self.schedulers)),
            "router_queue_depth": float(sum(s.queue_depth
                                            for s in self.schedulers)),
            "router_occupancy": (sum(s.occupancy for s in self.schedulers)
                                 / n),
        }
        if brief:
            return out
        ttfts = [t for s in self.schedulers for t in s._ttfts]
        out["router_ttft_p50_s"] = _quantile(ttfts, 0.5)
        out["router_ttft_p99_s"] = _quantile(ttfts, 0.99)
        if self.ttft_slo_s > 0.0:
            out["router_ttft_slo_ok_frac"] = (
                sum(1 for t in ttfts if t <= self.ttft_slo_s) / len(ttfts)
                if ttfts else 1.0)
        # fleet-summed engine counters (prefill chunks, page hits, ...)
        counters: dict = {}
        for s in self.schedulers:
            for k, v in getattr(s.engine, "counters", {}).items():
                counters[k] = counters.get(k, 0) + v
        out.update({f"router_{k}": float(v) for k, v in counters.items()})
        for i, s in enumerate(self.schedulers):
            st = s.stats()
            for k in _REPLICA_KEYS:
                if k in st:
                    out[f"replica{i}_{k}"] = st[k]
        if self.telemetry is not None:
            roll = self.telemetry.spans.rollup().get("router_wait")
            if roll is not None:
                out["router_wait_p50_s"] = roll["p50_s"]
                out["router_wait_p99_s"] = roll["p99_s"]
        return out


def poisson_replay(router, arrivals, *, clock=time.perf_counter,
                   sleep=time.sleep) -> float:
    """:func:`dtf_tpu.serve.client.replay` works unchanged on a Router
    (same submit/tick/pending surface) — re-exported here so fleet benches
    read naturally."""
    from dtf_tpu.serve.client import replay

    return replay(router, arrivals, clock=clock, sleep=sleep)


__all__ = ["Router", "poisson_replay"]
