"""Multi-replica router — the serving tier above :class:`DecodeEngine`.

One engine is one KV-cache pool on one device set; the ROADMAP's
millions-of-users north star needs N of them behind one front door. A
:class:`Router` owns N ``(DecodeEngine, Scheduler)`` replicas that SHARE
one restored param tree (weights are read-only at serve time — N replicas
cost N KV caches, not N param copies) while keeping fully independent KV
state, and admits each request to the replica with the **least slot
occupancy**, breaking ties by **queue depth** (then replica index, for
determinism). Every replica keeps the engine's fixed-shape discipline:
``trace_counts`` stays ``{prefill: 1, decode: 1}`` per replica and the
``gpt_serve`` comms fence covers each replica's decode graph identically.

Observability is the PR 5 span surface, serving edition:

- ``router_wait`` — queue time between submit and a replica accepting the
  request into a slot (recorded by the scheduler at admission; host
  clocks only, zero added device readbacks);
- per-replica TTFT/occupancy/SLO rollups in :meth:`Router.stats`
  (``replica{i}_*`` keys) next to the fleet aggregates — ``ttft_slo_s``
  sets the TTFT objective each replica reports compliance against.

The router is drop-in for the scheduler in the pump loop: it exposes the
same ``submit/tick/pending`` surface, so :func:`dtf_tpu.serve.client.replay`
drives a fleet exactly like a single scheduler (the bench A/B rides this).

Resilience (ISSUE 12): with more than one replica the router runs a
per-replica health state machine (:mod:`dtf_tpu.serve.health`) by
default — every replica tick is wall-timed on the router's clock, a
wedged or repeatedly-slow replica is **quarantined** (``_pick`` skips it,
its ticks stop, its in-flight requests are requeued onto survivors in
submit order), and after a probation delay it is re-admitted on trial
(idle probation replicas are exercised via ``DecodeEngine.probe``).
Requeue is a full deterministic replay — the survivor re-prefills the
prompt (cached stems land in one page gather where the survivor's prefix
pool has them) and regenerates the token stream, bitwise identical to a
fault-free run of the same request. When NO replica is routable the
router sheds at the front door with a ``retry_after_s`` derived from the
earliest probation ETA. docs/RESILIENCE.md "Serving" walks the states
and the chaos matrix that pins the behavior.

Weight hot-swap (ISSUE 14): :meth:`Router.start_swap` rolls a new param
version across the fleet with ZERO downtime — one replica at a time is
drained through the same requeue path, swapped in place
(``DecodeEngine.swap_params``: no recompiles, ``trace_counts`` pinned),
probed and re-admitted; the first swapped replica serves a
:class:`SwapConfig`-sized CANARY window under the health watchdog and a
TTFT-SLO gate, and a breach (or any swap-step failure — the
``wedge_in_swap`` chaos verb) rolls every swapped replica back onto the
previous version fleet-wide. ``maybe_swap_published`` drives it from a
:class:`dtf_tpu.publish.PublishWatcher`. Completed records stamp the
param version that decoded them; docs/RESILIENCE.md §9 walks the
contracts.
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
import logging
import time
from typing import Optional, Sequence

from dtf_tpu.metrics import quantile as _quantile
from dtf_tpu.serve import health as health_lib
from dtf_tpu.serve.engine import DecodeEngine
from dtf_tpu.serve.scheduler import (FAILED_STATUSES, Request,
                                     RequestFailed, Scheduler)
from dtf_tpu.telemetry.spans import SpanRecorder

log = logging.getLogger("dtf_tpu")

#: per-replica stat keys surfaced as ``replica{i}_<key>`` (the SLO panel);
#: everything else stays per-scheduler to keep the JSON line bounded.
_REPLICA_KEYS = ("serve_completed", "serve_occupancy_mean",
                 "serve_ttft_p50_s", "serve_ttft_p99_s",
                 "serve_queue_peak", "serve_ttft_slo_ok_frac",
                 "serve_shed", "serve_timeouts", "serve_requeued_in")


@dataclasses.dataclass(frozen=True)
class SwapConfig:
    """Knobs of the rolling weight swap (ISSUE 14, module docstring).

    The FIRST swapped replica is the **canary**: for ``canary_ticks``
    router ticks it serves live traffic on the new version alone, and a
    breach inside that window — the canary's health state leaving
    HEALTHY (the watchdog's slow/wedge/fault verdicts), or, with a TTFT
    SLO configured, its post-swap ok-fraction dropping under
    ``slo_floor`` over at least ``slo_min_samples`` completions —
    triggers an automatic FLEET-WIDE rollback to the previous version.
    Only after a clean window does the swap roll across the rest of the
    fleet, one replica per tick."""

    canary_ticks: int = 8
    slo_floor: float = 0.0          # 0 = health-gate only
    slo_min_samples: int = 1

    def __post_init__(self):
        if self.canary_ticks < 1:
            raise ValueError(
                f"canary_ticks={self.canary_ticks} must be >= 1 (a swap "
                "with no canary window cannot be health-gated)")
        if not 0.0 <= self.slo_floor <= 1.0:
            raise ValueError(f"slo_floor={self.slo_floor} must be in "
                             "[0, 1]")
        if self.slo_min_samples < 1:
            raise ValueError(
                f"slo_min_samples={self.slo_min_samples} must be >= 1")


class Router:
    """Least-occupancy admission over N engine replicas (module docstring).

    Build from live engines (params already shared by construction — pass
    the same tree to each) or via :meth:`build`. ``ttft_slo_s``/``clock``/
    scheduler knobs apply to every replica's scheduler uniformly.
    """

    #: router ticks between periodic ``cp_profile`` events on the event
    #: plane (the tick profiler's durable rollup; stats() is the live one).
    CP_PROFILE_EVERY = 256

    def __init__(self, engines: Sequence[DecodeEngine], writer=None, *,
                 telemetry=None, ttft_slo_s: float = 0.0,
                 clock=time.monotonic, health=None,
                 prefill_replicas: int = 0, log_sink=None, events=None,
                 **scheduler_kw):
        if not engines:
            raise ValueError("Router needs at least one engine replica")
        # prefill/decode DISAGGREGATION: the FIRST ``prefill_replicas``
        # engines are dedicated prefill replicas — requests whose prompt
        # has >= 1 uncached full page route there first, their KV pages
        # land in the SHARED page store (the transport), and the request
        # is then handed off to a decode replica whose admission gathers
        # the pinned chain instead of re-running the transformer. A burst
        # of long prompts therefore saturates prefill replicas, not the
        # fleet's decode ticks.
        self._prefill_replicas = prefill_replicas
        if prefill_replicas:
            if not 0 < prefill_replicas < len(engines):
                raise ValueError(
                    f"prefill_replicas={prefill_replicas} must leave at "
                    f"least one decode replica (have {len(engines)})")
            stores = {id(getattr(e, "page_store", None)) for e in engines}
            if any(getattr(e, "page_store", None) is None
                   for e in engines) or len(stores) != 1:
                raise ValueError(
                    "prefill/decode disaggregation needs every replica "
                    "to mount ONE shared page store (the KV transport) — "
                    "build via Router.build(prefill_replicas=..., "
                    "prefix_pages=...)")
        self._roles = ["prefill" if i < prefill_replicas else "decode"
                       for i in range(len(engines))]
        self.telemetry = telemetry
        self.clock = clock
        #: ONE serve-log sink shared by the fleet (ISSUE 19): the pump is
        #: one thread, records carry their replica id, and a single shard
        #: sequence keeps the mounted stream source's addressing global.
        self.log_sink = log_sink
        #: ONE fleet EventLog (ISSUE 20, dtf_tpu/telemetry/events.py):
        #: requeue drains, swap lifecycle and health transitions land on
        #: the run timeline, each stamped with the router tick.
        self.events = events
        #: the CONTROL-PLANE TICK PROFILER (ISSUE 20): per-tick phase
        #: attribution on the PR 5 span machinery, timed on the router's
        #: own injectable clock — host arithmetic only, zero added device
        #: readbacks (counter-proven in tests/test_events.py).
        self._cp = SpanRecorder(clock=clock)
        self.schedulers = [
            Scheduler(e, writer, telemetry=telemetry,
                      ttft_slo_s=ttft_slo_s, clock=clock,
                      postmortem_name=None, log_sink=log_sink,
                      replica_index=i, **scheduler_kw)
            for i, e in enumerate(engines)]
        # replica health: ON by default for a real fleet (>1 replica —
        # quarantine needs survivors to requeue onto); pass a
        # HealthConfig to tune thresholds or force it for a single
        # replica, False to disable outright.
        if health is False:
            self.health: Optional[health_lib.HealthTracker] = None
        elif isinstance(health, health_lib.HealthTracker):
            self.health = health
            if events is not None and health.events is None:
                health.events = events   # one timeline for the fleet
        elif isinstance(health, health_lib.HealthConfig):
            self.health = health_lib.HealthTracker(
                len(engines), health, clock=clock, events=events)
        elif health is None and len(engines) == 1:
            self.health = None
        else:    # None with a fleet, or True
            self.health = health_lib.HealthTracker(len(engines), clock=clock,
                                                   events=events)
        if telemetry is not None:
            # ONE aggregate postmortem provider for the fleet (each
            # replica's provider would collide on the name): in-flight
            # request ids + slot ages per replica, host facts only.
            telemetry.add_postmortem_provider(
                "serve_router", self.postmortem_state)
        self.ttft_slo_s = ttft_slo_s
        self._where: dict[int, tuple[int, int]] = {}
        #: front-door sheds (no routable replica): terminal records the
        #: schedulers never saw, bounded like their completed retention.
        self._router_shed: dict[int, dict] = {}
        self._shed_cap = int(scheduler_kw.get("completed_cap", 100_000))
        self._shed_router = 0
        self._requeued = 0
        #: in-flight prefill-phase handoffs: fleet rid -> (the ORIGINAL
        #: request, its submit moment). While present, the rid points at
        #: a max_new=1 prefill JOB on a prefill replica; on the job's
        #: terminal status the original request is submitted to a decode
        #: replica with the original submit_t (TTFT and deadlines honest
        #: across the handoff) and hits the pages the job just saved.
        self._handoff: dict[int, tuple[Request, float]] = {}
        self._handoffs = 0
        self._next_id = 0
        # ---- rolling weight swap (ISSUE 14) -------------------------
        #: the fleet's COMMITTED param version (what a fully-converged
        #: fleet serves); per-replica truth lives on each engine.
        self._version = 0
        #: in-progress swap state machine (None = steady state)
        self._swap: Optional[dict] = None
        #: replica currently being drained+swapped (never routable)
        self._swapping: Optional[int] = None
        #: replicas stuck on weights the fleet REJECTED (their reverse
        #: swap failed during a rollback): version -> repair payload.
        #: Such a replica is never routable — probation would otherwise
        #: re-admit it serving a rolled-back version — until
        #: :meth:`_retry_version_repair` aligns it with the fleet.
        self._version_repair: dict[int, tuple] = {}
        #: health-less fleets have no quarantine backoff to pace repair
        #: retries: (next_allowed_tick, delay_ticks) per pending repair
        self._repair_backoff: dict[int, tuple[int, int]] = {}
        self._ticks = 0
        self._swaps = 0
        self._swap_rollbacks = 0
        self._last_swap: Optional[dict] = None
        #: version-skew tripwire: WARN once when the fleet spans more
        #: than one version OUTSIDE an in-progress swap, re-armed when
        #: the fleet converges again (ISSUE 14 satellite)
        self._skew_warned = False

    @classmethod
    def build(cls, cfg, params, *, n_replicas: int, n_slots: int,
              max_len: int, prefill_chunk: int = 16, mesh=None,
              kv_page_size: int = 0, prefix_pages: int = 0,
              page_save_after: int = 2, draft_cfg=None, draft_params=None,
              spec_k: int = 0, prefill_replicas: int = 0,
              **router_kw) -> "Router":
        """N replicas over ONE param tree. Each replica gets its own KV
        state (and page pool, when enabled) and its own AOT programs; the
        params device arrays are shared. ``draft_cfg``/``draft_params``/
        ``spec_k`` arm speculative decoding on the DECODE replicas (a
        dedicated prefill replica never decodes, so it skips the draft
        programs). ``prefill_replicas=N`` disaggregates: the first N
        replicas are prefill-role, ALL replicas mount one shared page
        store (the KV transport; saves become eager — ``save_after`` is
        forced to 1, a transport that waits for a second sighting would
        hand off nothing), and the router routes by request phase."""
        if n_replicas < 1:
            raise ValueError(f"n_replicas={n_replicas} must be >= 1")
        if prefill_replicas and not prefix_pages:
            raise ValueError(
                "prefill_replicas needs prefix_pages > 0: the page pool "
                "IS the prefill→decode KV transport")
        if prefill_replicas and not 0 < prefill_replicas < n_replicas:
            # fail BEFORE compiling N engines (the ctor re-checks)
            raise ValueError(
                f"prefill_replicas={prefill_replicas} must leave at "
                f"least one decode replica (have {n_replicas})")
        if prefill_replicas:
            page_save_after = 1
        engines, store = [], None
        for r in range(n_replicas):
            pre = r < prefill_replicas
            engines.append(DecodeEngine(
                cfg, params, n_slots=n_slots, max_len=max_len,
                prefill_chunk=prefill_chunk, mesh=mesh,
                kv_page_size=kv_page_size, prefix_pages=prefix_pages,
                page_save_after=page_save_after, shared_pages=store,
                draft_cfg=None if pre else draft_cfg,
                draft_params=None if pre else draft_params,
                spec_k=0 if pre else spec_k))
            if prefill_replicas and store is None:
                store = engines[0].page_store
        return cls(engines, prefill_replicas=prefill_replicas, **router_kw)

    # ------------------------------------------------------------ admission

    def _emit(self, kind: str, /, **fields) -> None:
        """One fleet event, stamped with the router tick (the pump's own
        causal counter — the timeline can line events up with the tick
        profiler even when the wall clock is injected)."""
        if self.events is not None:
            self.events.emit(kind, tick=self._ticks, **fields)

    def _routable(self, i: int) -> bool:
        if i == self._swapping:     # mid-drain/swap: not a candidate
            return False
        if i in self._version_repair:
            # holding weights the fleet rolled back from: traffic (and
            # probation probes) must wait for the version repair
            return False
        return self.health is None or self.health.routable(i)

    def _pick(self, phase: str = "decode") -> Optional[int]:
        """Least occupancy over ROUTABLE replicas (health rank first:
        healthy before degraded before probation); queue depth breaks the
        tie (every replica saturated → the shortest line), replica index
        breaks that (deterministic tests). With disaggregation on, only
        replicas of the request's PHASE role are candidates — unless that
        role has no routable member, in which case the whole routable
        fleet serves it (a quarantined prefill tier degrades to full
        prefill on decode replicas; it never stops the fleet). None when
        nothing at all is routable — the caller sheds at the front
        door."""
        # cp_pick attributes EVERY admission decision (submit, handoff
        # promotion, requeue) — it may nest inside cp_page_ops; the
        # phases are attributions, not a partition
        t0 = self.clock()
        try:
            cands = [i for i in range(len(self.schedulers))
                     if self._routable(i)]
            if not cands:
                return None
            if self._prefill_replicas:
                role = [i for i in cands if self._roles[i] == phase]
                cands = role or cands
            rank = (self.health.rank if self.health is not None
                    else (lambda i: 0))
            return min(cands,
                       key=lambda i: (rank(i), self.schedulers[i].occupancy,
                                      self.schedulers[i].queue_depth, i))
        finally:
            self._cp.add("cp_pick", self.clock() - t0)

    def _wants_prefill_replica(self, req: Request) -> bool:
        """Phase classification: a request is PREFILL-HEAVY when at least
        one full page of its prompt is not already in the shared store —
        the work a dedicated prefill replica exists to absorb. Cached
        stems and sub-page prompts go straight to decode replicas (their
        admission is one page gather + a tail chunk)."""
        if not self._prefill_replicas:
            return False
        # pages are EPOCH-keyed (ISSUE 14): while ROUTABLE replicas'
        # versions diverge (a rolling swap in flight), a prefill job
        # would save pages at one version that the decode admission
        # gathers at another — a guaranteed miss that burns prefill-tier
        # work AND a promote hop. Route straight to decode (full prefill
        # there: the same tokens, one fewer hop) until they converge —
        # the window is bounded by the roll. Non-routable replicas
        # (quarantined / awaiting version repair) carry no traffic, so
        # their stray version must not disable disaggregation.
        versions = {getattr(s.engine, "param_version", 0)
                    for i, s in enumerate(self.schedulers)
                    if self._routable(i)}
        if len(versions) > 1:
            return False
        eng = self.schedulers[0].engine
        prompt = tuple(int(t) for t in req.prompt)
        full = max(0, (len(prompt) - 1) // eng.page_size)
        if full < 1:
            return False
        have, _ = eng._prefix.longest(
            prompt, cap=full, epoch=getattr(eng, "param_version", 0))
        return have < full

    def _shed_at_door(self, rid: int) -> None:
        eta = (self.health.quarantined_eta_s()
               if self.health is not None else None)
        self._router_shed[rid] = {
            "status": "shed", "tokens": [],
            "retry_after_s": round(eta if eta is not None else 1.0, 3)}
        self._where.pop(rid, None)
        self._shed_router += 1
        while len(self._router_shed) > self._shed_cap:
            self._router_shed.pop(next(iter(self._router_shed)))

    def submit(self, req: Request) -> int:
        # the fleet-global rid IS the request's trace id: every span the
        # replica scheduler and engine record for it carries this one id,
        # so a request renders end-to-end across the tiers in Perfetto.
        # Increment only after the replica ACCEPTED — a rejected submit
        # (over-long prompt) must not consume a fleet id.
        rid = self._next_id
        if self._wants_prefill_replica(req):
            i = self._pick("prefill")
            if i is None:
                self._next_id += 1
                self._shed_at_door(rid)
                return rid
            # the PREFILL JOB: same prompt/sampling/deadlines, one token —
            # its whole value is the page-save side effect. The original
            # request rides self._handoff until the job is terminal.
            t0 = self.clock()
            job = dataclasses.replace(req, max_new=1)
            local = self.schedulers[i].submit(job, trace_id=rid,
                                              submit_t=t0)
            self._next_id += 1
            self._where[rid] = (i, local)
            self._handoff[rid] = (req, t0)
            self._handoffs += 1
            return rid
        i = self._pick()
        if i is None:
            # nothing routable: shed at the front door with the earliest
            # probation ETA as the honest retry hint
            self._next_id += 1
            self._shed_at_door(rid)
            return rid
        local = self.schedulers[i].submit(req, trace_id=rid)
        self._next_id += 1
        self._where[rid] = (i, local)
        return rid

    def _promote_handoffs(self) -> None:
        """Move every finished prefill job's ORIGINAL request onto a
        decode replica. ``done`` promotes (the pages are saved; the
        decode admission gathers them) and so does ``shed`` (the prefill
        queue was full — the decode tier may still have room, where the
        request prefills from scratch). A ``timeout``/``error`` job is
        ADOPTED as the request's own verdict instead: the deadline was
        measured from the original submit and a poisoned prefill raises
        wherever it lands, so a decode-side replay could only repeat the
        same outcome while double-counting it in fleet stats — poll()
        keeps reading the job's terminal record through ``_where``."""
        if not self._handoff:
            return
        for rid in list(self._handoff):
            i, local = self._where[rid]
            st = self.schedulers[i].poll(local)
            if st["status"] not in ("done",) + FAILED_STATUSES:
                continue
            req, t0 = self._handoff.pop(rid)
            if st["status"] in ("timeout", "error"):
                continue               # adopted verdict; record retained
            self.schedulers[i].release(local)   # drop a DONE job's record
            j = self._pick()
            if j is None:
                self._shed_at_door(rid)
                continue
            local2 = self.schedulers[j].submit(req, trace_id=rid,
                                               submit_t=t0)
            self._where[rid] = (j, local2)

    def replica_of(self, rid: int) -> int:
        """Which replica holds request ``rid`` (admission audit)."""
        return self._where[rid][0]

    def postmortem_state(self) -> dict:
        """Fleet postmortem context: per-replica in-flight request ids,
        slot ages and health verdicts (host facts only — the
        flight-recorder dump contract)."""
        out = {f"replica{i}": s.postmortem_state()
               for i, s in enumerate(self.schedulers)}
        out["router"] = {"shed_at_door": self._shed_router,
                         "requeued": self._requeued,
                         "version": self._version,
                         "replica_versions": [
                             getattr(s.engine, "param_version", None)
                             for s in self.schedulers],
                         "swaps": self._swaps,
                         "swap_rollbacks": self._swap_rollbacks,
                         "swap_in_progress": self._swap is not None,
                         "version_repair_pending": sorted(
                             self._version_repair)}
        if self._last_swap is not None:
            out["router"]["last_swap"] = dict(self._last_swap)
        if self.health is not None:
            out["router"]["health"] = self.health.states()
            out["router"]["health_counters"] = dict(self.health.counters)
            out["router"]["health_transitions"] = \
                list(self.health.transitions)[-10:]
        return out

    # ------------------------------------------------------ quarantine drain

    def quarantine(self, i: int, cause: str = "forced") -> None:
        """Quarantine replica ``i`` now and requeue its in-flight
        requests onto survivors (operator/test API; the health watchdog
        reaches the same path through :meth:`tick`'s verdicts)."""
        if self.health is None:
            raise RuntimeError(
                "Router health is disabled (single replica without an "
                "explicit HealthConfig) — nothing to quarantine with")
        self.health.quarantine(i, cause)
        self._requeue_from(i)

    def _requeue_from(self, i: int) -> None:
        """Drain replica ``i`` (quarantined, or mid-swap): every
        in-flight request is re-submitted to a survivor in submit order
        with its ORIGINAL fleet rid, trace id and submit time — the
        survivor re-prefills (cached stems in one page gather where its
        prefix pool has them) and regenerates the deterministic token
        stream, so completed tokens are bitwise identical to a
        fault-free run. With no routable survivor the request sheds at
        the front door."""
        moved = shed = 0
        for rec in self.schedulers[i].evict_for_requeue():
            rid = rec.trace_id     # the fleet-global id (we threaded it)
            # a drained prefill JOB stays in its phase: re-route it to a
            # surviving prefill replica (or, via _pick's role fallback,
            # anywhere routable when the whole prefill tier is down)
            phase = "prefill" if rid in self._handoff else "decode"
            j = self._pick(phase)  # never i: quarantined is not routable
            if j is None:
                self._handoff.pop(rid, None)
                self._shed_at_door(rid)
                shed += 1
                continue
            local = self.schedulers[j].submit(
                rec.req, trace_id=rid, submit_t=rec.submit_t, requeued=True)
            self._where[rid] = (j, local)
            self._requeued += 1
            moved += 1
        if moved or shed:
            self._emit("requeue_drain", replica=i, requeued=moved,
                       shed=shed)

    def _probe(self, i: int) -> None:
        """Exercise an idle probation replica with one timed decode probe
        so re-admission does not have to wait for (and gamble) live
        traffic. Engines without a ``probe`` (fakes) skip — their
        probation resolves through routed requests instead."""
        probe = getattr(self.schedulers[i].engine, "probe", None)
        if probe is None:
            return
        t0 = self.clock()
        try:
            probe()
        except Exception as e:  # noqa: BLE001 — a probe failure is the
            # quarantine signal working; nothing to requeue (idle replica)
            self.health.note_fault(i, e)
            return
        self.health.note_tick(i, self.clock() - t0)

    # ------------------------------------------------------ rolling weight swap

    def stamp_version(self, version: int) -> None:
        """Stamp the param version the fleet was BUILT with (serving a
        published version from startup) onto every replica — no swap, no
        drain; call before traffic so record stamps, page epochs and the
        skew tripwire carry the real version."""
        for s in self.schedulers:
            setter = getattr(s.engine, "set_param_version", None)
            if setter is not None:
                setter(version)
        self._version = int(version)

    @property
    def swap_in_progress(self) -> bool:
        return self._swap is not None

    @property
    def version(self) -> int:
        """The fleet's committed param version (per-replica truth is in
        ``stats()``'s ``replica{i}_version`` panel)."""
        return self._version

    def start_swap(self, params, *, version: Optional[int] = None,
                   draft_params=None,
                   config: Optional[SwapConfig] = None) -> int:
        """Begin a ROLLING swap of the fleet onto ``params`` (module
        docstring): one replica per tick is drained via the quarantine
        requeue path (its in-flight requests replay on survivors — the
        fleet never stops serving), swapped with zero recompiles
        (``DecodeEngine.swap_params``), probed, and re-admitted. The
        first swapped replica is the health-gated CANARY
        (:class:`SwapConfig`); a breach inside its window rolls every
        already-swapped replica back to the previous version fleet-wide.

        The swap advances inside :meth:`tick` (one step per tick, so
        live traffic interleaves); with no traffic pending, pump
        :meth:`finish_swap`. ``version`` must be monotone (default:
        committed + 1); ``draft_params`` rides the same transaction on
        spec engines. Returns the target version."""
        if self._swap is not None:
            raise RuntimeError(
                f"a rolling swap to version {self._swap['version']} is "
                "already in progress")
        n = len(self.schedulers)
        if n < 2:
            raise ValueError(
                "a rolling swap needs >= 2 replicas (one drains while "
                "the others serve); a single engine swaps via "
                "DecodeEngine.swap_params after draining")
        version = self._version + 1 if version is None else int(version)
        if version <= self._version:
            raise ValueError(
                f"swap version {version} is not monotone (fleet is at "
                f"{self._version}) — published versions only move "
                "forward")
        cfg = config or SwapConfig()
        rank = (self.health.rank if self.health is not None
                else (lambda i: 0))
        # healthiest replica first: the canary must start from a clean
        # health state or the gate would trip on pre-existing trouble
        order = sorted(range(n), key=lambda i: (rank(i), i))
        self._swap = {
            "version": version, "params": params, "draft": draft_params,
            "cfg": cfg, "order": order, "canary": order[0],
            "canary_swapped": False, "ticks_left": cfg.canary_ticks,
            "ttft_mark": 0, "done": [],
            "prev_params": [s.engine._params for s in self.schedulers],
            "prev_draft": [getattr(s.engine, "_draft_params", None)
                           if getattr(s.engine, "spec_k", 0) else None
                           for s in self.schedulers],
            "prev_version": [getattr(s.engine, "param_version", 0)
                             for s in self.schedulers],
            "watcher": None,
        }
        self._emit("swap_start", version=version, canary=order[0],
                   canary_ticks=cfg.canary_ticks,
                   draft=draft_params is not None)
        log.info("rolling swap to param version %d started (canary "
                 "replica %d, %d-tick window)", version, order[0],
                 cfg.canary_ticks)
        return version

    def maybe_swap_draft(self, watcher, *,
                         config: Optional[SwapConfig] = None
                         ) -> Optional[int]:
        """Poll a :class:`dtf_tpu.publish.PublishWatcher` mounted on a
        DRAFT publish directory (``train_gpt --distill_draft``'s output)
        and roll a **draft-only** swap when it hands over a new version:
        the fleet's base params ride the transaction UNCHANGED and only
        ``draft_params`` flips, so emitted tokens are byte-identical by
        construction (the verifier owns the rng chain) and acceptance is
        the only thing that moves. The fleet version still advances by
        one (monotone — records stamp which draft served them, and the
        prefix-page epoch rolls with it); the watcher is credited with
        ITS version number, which need not match the fleet's."""
        if self._swap is not None:
            return None
        got = watcher.load_new()
        if got is None:
            return None
        dversion, step, draft_params = got
        # every replica shares ONE base tree by construction — replica
        # 0's live params ARE the fleet's params
        base = self.schedulers[0].engine._params
        v = self.start_swap(base, version=self._version + 1,
                            draft_params=draft_params, config=config)
        self._swap["watcher"] = watcher
        self._swap["watcher_version"] = dversion
        self._swap["step"] = step
        log.info("draft-only rolling swap started: draft publish version "
                 "%d rides fleet version %d (base params unchanged)",
                 dversion, v)
        return v

    def maybe_swap_published(self, watcher, *,
                             config: Optional[SwapConfig] = None,
                             draft_factory=None) -> Optional[int]:
        """Poll a :class:`dtf_tpu.publish.PublishWatcher` and start a
        rolling swap when it hands over a NEW verified version (corrupt
        publishes were already skipped with a WARN inside the watcher —
        the fleet keeps serving). ``draft_factory(params) ->
        draft_params`` rebuilds the draft from the new weights (the
        ``--draft_layers`` early-exit case). No-op while a swap is in
        progress. Returns the version a swap was started for, else
        None."""
        if self._swap is not None:
            return None
        got = watcher.load_new()
        if got is None:
            return None
        version, step, params = got
        if version <= self._version:
            watcher.note_applied(version)
            return None
        draft = draft_factory(params) if draft_factory is not None else None
        v = self.start_swap(params, version=version, draft_params=draft,
                            config=config)
        self._swap["watcher"] = watcher
        self._swap["step"] = step
        return v

    def finish_swap(self, max_ticks: int = 100000) -> None:
        """Pump ticks until the in-progress swap commits or rolls back
        (ticks with no traffic still advance the swap machine)."""
        for _ in range(max_ticks):
            if self._swap is None:
                return
            self.tick()
        raise RuntimeError(f"swap still in progress after {max_ticks} "
                           "ticks")

    def _swap_span(self):
        if self.telemetry is None:
            return contextlib.nullcontext()
        return self.telemetry.spans.span("serve_swap")

    def _swap_replica(self, i: int, params, draft, version: int, *,
                      probe: bool = True, mark=None) -> None:
        """Drain replica ``i`` onto the rest of the fleet, swap its
        weights, probe, re-admit — the per-replica step of the rolling
        swap. In-flight requests requeue with their ORIGINAL rid/
        submit_t (the PR 12 path), so a request spanning the swap
        boundary replays WHOLE on exactly one version. ``mark`` (the
        forward-swap callers' bookkeeping) runs the moment
        ``swap_params`` returns — BEFORE the probe — so a replica whose
        probe then raises is already recorded as swapped and a rollback
        includes it (its weights DID flip)."""
        self._swapping = i
        try:
            with self._swap_span():
                self._requeue_from(i)
                self.schedulers[i].engine.swap_params(
                    params, draft_params=draft, version=version)
        finally:
            self._swapping = None
        # ANY successful swap supersedes a pending version repair: the
        # replica now holds the weights this swap installed — a later
        # repair retry would revert it to the STALE rolled-back payload
        # and split the fleet permanently
        self._version_repair.pop(i, None)
        self._repair_backoff.pop(i, None)
        if mark is not None:
            mark()
        quarantined = (self.health is not None
                       and self.health.state(i)
                       == health_lib.QUARANTINED)
        if probe and not quarantined:
            # the same compiled decode, timed and fed to the watchdog: a
            # replica that comes back wedged is caught BEFORE live
            # traffic lands on it (and, for the canary, trips the gate)
            fn = getattr(self.schedulers[i].engine, "probe", None)
            if fn is not None:
                t0 = self.clock()
                fn()        # an exception here = swap failure (caller
                #             rolls the fleet back)
                if (self.health is not None
                        and self.health.note_tick(i, self.clock() - t0)
                        == health_lib.QUARANTINED):
                    self._requeue_from(i)   # nothing in flight; no-op

    def _advance_swap(self) -> None:
        """One step of the rolling-swap state machine, run at the end of
        every tick. Any exception inside a replica's swap step (the
        ``wedge_in_swap`` chaos verb, a failed probe, a bad tree) rolls
        the partial fleet back onto ONE version instead of propagating —
        a swap can fail, the fleet cannot."""
        sw = self._swap
        if sw is None:
            return
        try:
            if not sw["canary_swapped"]:
                i = sw["canary"]

                def mark_canary():
                    sw["canary_swapped"] = True
                    sw["ttft_mark"] = self.schedulers[i].ttft_count
                    self._emit("swap_canary", version=sw["version"],
                               replica=i,
                               canary_ticks=sw["cfg"].canary_ticks)

                self._swap_replica(i, sw["params"], sw["draft"],
                                   sw["version"], mark=mark_canary)
                return
            if sw["ticks_left"] > 0:
                cause = self._canary_breach()
                if cause is not None:
                    self._rollback_swap(f"canary breach: {cause}")
                    return
                sw["ticks_left"] -= 1
                return
            nxt = next((i for i in sw["order"]
                        if i != sw["canary"] and i not in sw["done"]),
                       None)
            if nxt is None:
                self._commit_swap()
                return
            self._swap_replica(nxt, sw["params"], sw["draft"],
                               sw["version"],
                               mark=lambda: sw["done"].append(nxt))
        except Exception as e:  # noqa: BLE001 — swap-step failures roll
            # back; only the rollback itself may quarantine a replica
            self._rollback_swap(
                f"swap step failed: {type(e).__name__}: {e}")

    def _canary_breach(self) -> Optional[str]:
        """The canary gate (SwapConfig docstring): health verdict first,
        then the post-swap TTFT SLO floor. None = clean so far."""
        sw = self._swap
        i = sw["canary"]
        if (self.health is not None
                and self.health.state(i) != health_lib.HEALTHY):
            return f"canary replica {i} health {self.health.state(i)}"
        cfg = sw["cfg"]
        if self.ttft_slo_s > 0.0 and cfg.slo_floor > 0.0:
            # samples SINCE the canary swap, measured against the
            # monotone counter (the deque is maxlen-bounded: an index
            # mark into it goes stale once it wraps — a long-running
            # server would otherwise never see a canary sample again).
            # REQUEUED requests are excluded: their TTFT includes time
            # lost on some OTHER replica's failure (original submit_t —
            # the PR 12 contract), and a gate counting them would blame
            # the new weights for an unrelated fault and blacklist a
            # perfectly good version.
            sched = self.schedulers[i]
            new = sched.ttft_count - sw["ttft_mark"]
            d, rq = sched._ttfts, sched._ttft_requeued
            lo = max(0, len(d) - min(new, len(d)))
            samples = [t for t, requeued in zip(
                itertools.islice(d, lo, None),
                itertools.islice(rq, lo, None)) if not requeued]
            if len(samples) >= cfg.slo_min_samples:
                ok = sum(1 for t in samples
                         if t <= self.ttft_slo_s) / len(samples)
                if ok < cfg.slo_floor:
                    return (f"canary TTFT SLO ok-frac {ok:.3f} < floor "
                            f"{cfg.slo_floor} over {len(samples)} "
                            "completions")
        return None

    def _rollback_swap(self, cause: str) -> None:
        """Fleet-wide rollback: every already-swapped replica (canary
        included) drains and takes its PREVIOUS weights back, so the
        fleet converges on one version. A replica that cannot even swap
        back is quarantined out of traffic — the fleet keeps serving."""
        sw = self._swap
        self._swap = None
        swapped = ([sw["canary"]] if sw["canary_swapped"] else []) \
            + sw["done"]
        log.warning(
            "rolling swap to param version %d ROLLED BACK after %d "
            "replica(s): %s", sw["version"], len(swapped), cause)
        for i in reversed(swapped):
            try:
                self._swap_replica(i, sw["prev_params"][i],
                                   sw["prev_draft"][i],
                                   sw["prev_version"][i], probe=False)
            except Exception as e:  # noqa: BLE001 — a replica wedged in
                # BOTH directions leaves traffic via quarantine, not by
                # failing the rollback of the rest of the fleet; the
                # REPAIR record keeps it unroutable (probation must not
                # re-admit a replica serving the rejected version) until
                # _retry_version_repair re-aligns its weights
                log.warning("replica %d failed to roll back (%r)", i, e)
                self._version_repair[i] = (sw["prev_params"][i],
                                           sw["prev_draft"][i],
                                           sw["prev_version"][i])
                if self.health is not None:
                    self.health.quarantine(i, f"rollback failed: {e!r}")
                    self._requeue_from(i)
        self._swap_rollbacks += 1
        self._last_swap = {"version": sw["version"],
                           "outcome": "rolled_back", "cause": cause}
        self._emit("swap_rollback", version=sw["version"], cause=cause,
                   swapped=len(swapped))
        if sw["watcher"] is not None:
            # a rolled-back version must not immediately re-swap on the
            # next poll: only a NEWER republish may try again (a draft
            # watcher is credited in ITS version numbering)
            sw["watcher"].skipped.add(sw.get("watcher_version",
                                            sw["version"]))
        self._invalidate_stale_pages()

    def _commit_swap(self) -> None:
        sw = self._swap
        self._swap = None
        self._version = sw["version"]
        self._swaps += 1
        self._last_swap = {"version": sw["version"], "outcome": "done"}
        if sw["watcher"] is not None:
            sw["watcher"].note_applied(sw.get("watcher_version",
                                              sw["version"]))
        self._invalidate_stale_pages()
        self._emit("swap_commit", version=sw["version"],
                   draft=sw["draft"] is not None)
        log.info("rolling swap complete: fleet serving param version %d",
                 sw["version"])

    def _retry_version_repair(self, i: int) -> bool:
        """Re-align a replica stuck on rolled-back weights (its reverse
        swap failed) with the fleet's committed version — attempted at
        every tick the health machine would otherwise let it back in,
        BEFORE any probe or traffic. True once aligned."""
        params, draft, version = self._version_repair[i]
        try:
            self._swap_replica(i, params, draft, version, probe=False)
        except Exception as e:  # noqa: BLE001 — still broken: stays
            # unroutable (the repair record); quarantine backoff paces
            # the next try (health), or the tick backoff (health-less)
            log.warning("replica %d version repair failed (%r)", i, e)
            if self.health is not None:
                self.health.quarantine(i, f"version repair failed: {e!r}")
            else:
                _, delay = self._repair_backoff.get(i, (0, 1))
                self._repair_backoff[i] = (self._ticks + delay,
                                           min(delay * 2, 1024))
            return False
        # the record was popped by _swap_replica on success
        log.info("replica %d re-aligned to param version %d after a "
                 "failed rollback", i, version)
        return True

    def _invalidate_stale_pages(self) -> None:
        """Reclaim prefix pages of other param versions once the fleet
        converged (lookups already epoch-gate them — this is the eager
        half of invalidation; pages.py docstring). One pass per DISTINCT
        store: a shared disaggregation pool must not be walked once per
        mounting replica."""
        seen: set[int] = set()
        for s in self.schedulers:
            store = getattr(s.engine, "page_store", None)
            if store is None or id(store) in seen:
                continue
            seen.add(id(store))
            freed = store.index.invalidate_stale(self._version)
            if freed:
                log.info("freed %d stale-version prefix page(s)", freed)

    def _skew_check(self) -> None:
        """The version-skew tripwire (ISSUE 14 satellite): WARN once when
        replicas serve more than one param version OUTSIDE an in-progress
        rolling swap; re-armed when the fleet converges."""
        vs = {getattr(s.engine, "param_version", None)
              for s in self.schedulers}
        vs.discard(None)
        if len(vs) > 1 and self._swap is None:
            if not self._skew_warned:
                self._skew_warned = True
                log.warning(
                    "fleet spans param versions %s outside a rolling "
                    "swap — replicas are serving DIFFERENT weights "
                    "(skew tripwire; re-armed on convergence)",
                    sorted(vs))
        elif len(vs) <= 1:
            self._skew_warned = False

    # ----------------------------------------------------------- pump surface

    @property
    def pending(self) -> int:
        return sum(s.pending for s in self.schedulers)

    def tick(self) -> None:
        """One scheduling round on every ROUTABLE replica with work —
        replicas are independent KV state, so their ticks never contend
        for slots. With health on, each tick is wall-timed and fed to the
        watchdog; a quarantine verdict (slow/wedged/faulted) immediately
        drains that replica onto survivors, so the pump loop never calls
        into a wedged engine again."""
        self._ticks += 1
        # the control-plane tick profiler (ISSUE 20): cp_engine_tick sums
        # the replica s.tick() calls (for the health branch, the SAME
        # wall-time samples the watchdog judges); cp_health_sweep is the
        # replica loop's remainder (routable checks, verdicts, probes);
        # cp_page_ops = handoff promotion; cp_bookkeeping = swap machine +
        # skew tripwire. Host clock arithmetic only.
        cp = self._cp
        h = self.health
        t_loop0 = self.clock()
        engine_s = 0.0
        if h is None:
            for i, s in enumerate(self.schedulers):
                if i in self._version_repair:
                    # paced by the tick backoff: a still-broken engine
                    # must not re-validate + re-place the whole param
                    # tree (and WARN) on every tick of a busy pump
                    if self._ticks >= self._repair_backoff.get(i, (0, 1))[0]:
                        self._retry_version_repair(i)
                    continue
                if s.pending:
                    t0 = self.clock()
                    s.tick()
                    engine_s += self.clock() - t0
        else:
            for i, s in enumerate(self.schedulers):
                if i in self._version_repair:
                    # stuck on a rolled-back version: the repair must land
                    # before the health machine may re-admit it (routable()
                    # flips quarantine→probation lazily — let it, but no
                    # probe/traffic this tick either way)
                    if h.routable(i):
                        self._retry_version_repair(i)
                    continue
                if not h.routable(i):
                    continue
                if not s.pending:
                    if h.state(i) == health_lib.PROBATION:
                        self._probe(i)
                    continue
                t0 = self.clock()
                try:
                    s.tick()
                except Exception as e:  # noqa: BLE001 — a decode-path
                    # engine failure has no single owning request:
                    # quarantine the replica and replay its in-flight
                    # work on survivors
                    engine_s += self.clock() - t0
                    h.note_fault(i, e)
                    self._requeue_from(i)
                    continue
                dur = self.clock() - t0
                engine_s += dur
                if h.note_tick(i, dur) == health_lib.QUARANTINED:
                    self._requeue_from(i)
        t_loop1 = self.clock()
        cp.add("cp_engine_tick", engine_s)
        cp.add("cp_health_sweep", max(0.0, (t_loop1 - t_loop0) - engine_s))
        t0 = self.clock()
        self._promote_handoffs()
        t1 = self.clock()
        cp.add("cp_page_ops", t1 - t0)
        self._advance_swap()
        self._skew_check()
        cp.add("cp_bookkeeping", self.clock() - t1)
        if self.events is not None and self._ticks % self.CP_PROFILE_EVERY == 0:
            self._emit("cp_profile", **{
                f"{name}_total_s": round(cp.total(name), 6)
                for name in ("cp_pick", "cp_engine_tick", "cp_health_sweep",
                             "cp_page_ops", "cp_bookkeeping")})

    def run_until_idle(self, max_ticks: int = 100000, *,
                       on_tick=None) -> None:
        for _ in range(max_ticks):
            if not self.pending:
                return
            self.tick()
            if on_tick is not None:
                on_tick()
        raise RuntimeError(f"requests still pending after {max_ticks} ticks")

    def poll(self, rid: int) -> dict:
        shed = self._router_shed.get(rid)
        if shed is not None:
            return dict(shed)
        if rid in self._handoff:
            # prefill phase of a disaggregated request: the job's local
            # statuses (and its one sampled token) are plumbing — the
            # caller sees a request that is still prefilling
            return {"status": "prefill", "tokens": []}
        i, local = self._where[rid]
        return self.schedulers[i].poll(local)

    def result(self, rid: int, max_ticks: int = 100000) -> list[int]:
        for _ in range(max_ticks):
            st = self.poll(rid)
            if st["status"] == "done":
                return st["tokens"]
            if st["status"] in FAILED_STATUSES:
                # shed/timeout/error are TERMINAL: raise now instead of
                # pumping max_ticks on a request that will never finish
                raise RequestFailed(rid, st)
            self.tick()
        raise RuntimeError(f"request {rid} not done after {max_ticks} ticks")

    def release(self, rid: int) -> None:
        if self._router_shed.pop(rid, None) is not None:
            return
        i, local = self._where.pop(rid)
        self.schedulers[i].release(local)

    def drain(self) -> None:
        self.run_until_idle()

    # --------------------------------------------------------------- metrics

    def trace_counts(self) -> list[dict]:
        """Per-replica program trace counters (page fences merged in) —
        the steady-state recompile pin, fleet edition."""
        return [{**s.engine.trace_counts,
                 **{f"page_{k}": v
                    for k, v in s.engine.page_trace_counts.items()}}
                for s in self.schedulers]

    def accept_by_version(self) -> dict:
        """Fleet-summed per-version speculative acceptance counts,
        ``{version: (proposed, accepted)}`` (ISSUE 19) — the raw ints
        behind ``router_spec_accept_rate_v{N}``."""
        fleet: dict = {}
        for s in self.schedulers:
            for v, (prop, acc) in s.accept_by_version().items():
                cur = fleet.get(v, (0, 0))
                fleet[v] = (cur[0] + prop, cur[1] + acc)
        return dict(sorted(fleet.items()))

    def stats(self, brief: bool = False) -> dict:
        """Fleet aggregates + the ``replica{i}_*`` SLO panel."""
        n = len(self.schedulers)
        out = {
            "router_replicas": float(n),
            "router_completed": float(sum(s._completed
                                          for s in self.schedulers)),
            "router_queue_depth": float(sum(s.queue_depth
                                            for s in self.schedulers)),
            "router_occupancy": (sum(s.occupancy for s in self.schedulers)
                                 / n),
        }
        if brief:
            return out
        # the hot-swap panel (ISSUE 14): committed + per-replica active
        # param versions (the skew tripwire's raw data — _skew_check
        # WARNs on divergence outside a swap), swap/rollback counters
        self._skew_check()
        out["router_version"] = float(self._version)
        out["router_swaps"] = float(self._swaps)
        out["router_swap_rollbacks"] = float(self._swap_rollbacks)
        out["router_swap_in_progress"] = float(self._swap is not None)
        for i, s in enumerate(self.schedulers):
            v = getattr(s.engine, "param_version", None)
            if v is not None:
                out[f"replica{i}_version"] = float(v)
        out["router_shed"] = float(self._shed_router
                                   + sum(s._shed for s in self.schedulers))
        out["router_timeouts"] = float(sum(s._timeouts
                                           for s in self.schedulers))
        out["router_request_errors"] = float(
            sum(s._request_errors for s in self.schedulers))
        out["router_requeued"] = float(self._requeued)
        if self._prefill_replicas:
            out["router_prefill_replicas"] = float(self._prefill_replicas)
            out["router_handoffs"] = float(self._handoffs)
            for i, role in enumerate(self._roles):
                out[f"replica{i}_role"] = role
        if self.health is not None:
            hc = self.health.counters
            out["router_quarantines"] = float(hc["quarantines"])
            out["router_probation_readmits"] = float(hc["readmits"])
            out["router_replica_faults"] = float(hc["faults"])
            for i in range(n):
                out[f"replica{i}_health"] = self.health.state(i)
        # fleet TTFT: with disaggregation on, prefill-role schedulers'
        # samples are JOB latencies (plumbing), not user-visible first
        # tokens — the decode replicas record the real TTFT (measured
        # from the ORIGINAL submit via the threaded submit_t)
        ttfts = [t for i, s in enumerate(self.schedulers)
                 if not (self._prefill_replicas
                         and self._roles[i] == "prefill")
                 for t in s._ttfts]
        out["router_ttft_p50_s"] = _quantile(ttfts, 0.5)
        out["router_ttft_p99_s"] = _quantile(ttfts, 0.99)
        if self.ttft_slo_s > 0.0:
            out["router_ttft_slo_ok_frac"] = (
                sum(1 for t in ttfts if t <= self.ttft_slo_s) / len(ttfts)
                if ttfts else 1.0)
        # the flywheel panel (ISSUE 19): fleet per-version acceptance —
        # a distilled draft's swap shows up as rate_v{new} > rate_v{old}
        for v, (prop, acc) in self.accept_by_version().items():
            if prop:
                out[f"router_spec_accept_rate_v{v}"] = acc / prop
        if self.log_sink is not None:
            out["router_log_sink_records"] = float(
                self.log_sink.stats()["records"])
        # fleet-summed engine counters (prefill chunks, page hits, ...)
        counters: dict = {}
        for s in self.schedulers:
            for k, v in getattr(s.engine, "counters", {}).items():
                counters[k] = counters.get(k, 0) + v
        out.update({f"router_{k}": float(v) for k, v in counters.items()})
        # the control-plane tick profiler panel (ISSUE 20): where the
        # pump's host time goes, per phase — the live view of what
        # bench_serve_cp fences and the cp_profile events make durable
        out["router_ticks"] = float(self._ticks)
        for name, roll in self._cp.rollup().items():
            out[f"{name}_total_s"] = roll["total_s"]
            out[f"{name}_mean_s"] = roll["mean_s"]
            out[f"{name}_p99_s"] = roll["p99_s"]
        if self.events is not None:
            out["router_events"] = float(self.events.stats()["events"])
        for i, s in enumerate(self.schedulers):
            st = s.stats()
            for k in _REPLICA_KEYS:
                if k in st:
                    out[f"replica{i}_{k}"] = st[k]
        if self.telemetry is not None:
            rollup = self.telemetry.spans.rollup()
            roll = rollup.get("router_wait")
            if roll is not None:
                out["router_wait_p50_s"] = roll["p50_s"]
                out["router_wait_p99_s"] = roll["p99_s"]
            swap_roll = rollup.get("serve_swap")
            if swap_roll is not None:
                out["serve_swap_p50_s"] = swap_roll["p50_s"]
                out["serve_swap_p99_s"] = swap_roll["p99_s"]
        return out


def poisson_replay(router, arrivals, *, clock=time.perf_counter,
                   sleep=time.sleep) -> float:
    """:func:`dtf_tpu.serve.client.replay` works unchanged on a Router
    (same submit/tick/pending surface) — re-exported here so fleet benches
    read naturally."""
    from dtf_tpu.serve.client import replay

    return replay(router, arrivals, clock=clock, sleep=sleep)


__all__ = ["Router", "SwapConfig", "poisson_replay"]
