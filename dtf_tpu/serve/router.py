"""Multi-replica router — the serving tier above :class:`DecodeEngine`.

One engine is one KV-cache pool on one device set; the ROADMAP's
millions-of-users north star needs N of them behind one front door. A
:class:`Router` owns N ``(DecodeEngine, Scheduler)`` replicas that SHARE
one restored param tree (weights are read-only at serve time — N replicas
cost N KV caches, not N param copies) while keeping fully independent KV
state, and admits each request to the replica with the **least slot
occupancy**, breaking ties by **queue depth** (then replica index, for
determinism). Every replica keeps the engine's fixed-shape discipline:
``trace_counts`` stays ``{prefill: 1, decode: 1}`` per replica and the
``gpt_serve`` comms fence covers each replica's decode graph identically.

Observability is the PR 5 span surface, serving edition:

- ``router_wait`` — queue time between submit and a replica accepting the
  request into a slot (recorded by the scheduler at admission; host
  clocks only, zero added device readbacks);
- per-replica TTFT/occupancy/SLO rollups in :meth:`Router.stats`
  (``replica{i}_*`` keys) next to the fleet aggregates — ``ttft_slo_s``
  sets the TTFT objective each replica reports compliance against.

The router is drop-in for the scheduler in the pump loop: it exposes the
same ``submit/tick/pending`` surface, so :func:`dtf_tpu.serve.client.replay`
drives a fleet exactly like a single scheduler (the bench A/B rides this).

Resilience (ISSUE 12): with more than one replica the router runs a
per-replica health state machine (:mod:`dtf_tpu.serve.health`) by
default — every replica tick is wall-timed on the router's clock, a
wedged or repeatedly-slow replica is **quarantined** (``_pick`` skips it,
its ticks stop, its in-flight requests are requeued onto survivors in
submit order), and after a probation delay it is re-admitted on trial
(idle probation replicas are exercised via ``DecodeEngine.probe``).
Requeue is a full deterministic replay — the survivor re-prefills the
prompt (cached stems land in one page gather where the survivor's prefix
pool has them) and regenerates the token stream, bitwise identical to a
fault-free run of the same request. When NO replica is routable the
router sheds at the front door with a ``retry_after_s`` derived from the
earliest probation ETA. docs/RESILIENCE.md "Serving" walks the states
and the chaos matrix that pins the behavior.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional, Sequence

from dtf_tpu.metrics import quantile as _quantile
from dtf_tpu.serve import health as health_lib
from dtf_tpu.serve.engine import DecodeEngine
from dtf_tpu.serve.scheduler import (FAILED_STATUSES, Request,
                                     RequestFailed, Scheduler)

#: per-replica stat keys surfaced as ``replica{i}_<key>`` (the SLO panel);
#: everything else stays per-scheduler to keep the JSON line bounded.
_REPLICA_KEYS = ("serve_completed", "serve_occupancy_mean",
                 "serve_ttft_p50_s", "serve_ttft_p99_s",
                 "serve_queue_peak", "serve_ttft_slo_ok_frac",
                 "serve_shed", "serve_timeouts", "serve_requeued_in")


class Router:
    """Least-occupancy admission over N engine replicas (module docstring).

    Build from live engines (params already shared by construction — pass
    the same tree to each) or via :meth:`build`. ``ttft_slo_s``/``clock``/
    scheduler knobs apply to every replica's scheduler uniformly.
    """

    def __init__(self, engines: Sequence[DecodeEngine], writer=None, *,
                 telemetry=None, ttft_slo_s: float = 0.0,
                 clock=time.monotonic, health=None,
                 prefill_replicas: int = 0, **scheduler_kw):
        if not engines:
            raise ValueError("Router needs at least one engine replica")
        # prefill/decode DISAGGREGATION: the FIRST ``prefill_replicas``
        # engines are dedicated prefill replicas — requests whose prompt
        # has >= 1 uncached full page route there first, their KV pages
        # land in the SHARED page store (the transport), and the request
        # is then handed off to a decode replica whose admission gathers
        # the pinned chain instead of re-running the transformer. A burst
        # of long prompts therefore saturates prefill replicas, not the
        # fleet's decode ticks.
        self._prefill_replicas = prefill_replicas
        if prefill_replicas:
            if not 0 < prefill_replicas < len(engines):
                raise ValueError(
                    f"prefill_replicas={prefill_replicas} must leave at "
                    f"least one decode replica (have {len(engines)})")
            stores = {id(getattr(e, "page_store", None)) for e in engines}
            if any(getattr(e, "page_store", None) is None
                   for e in engines) or len(stores) != 1:
                raise ValueError(
                    "prefill/decode disaggregation needs every replica "
                    "to mount ONE shared page store (the KV transport) — "
                    "build via Router.build(prefill_replicas=..., "
                    "prefix_pages=...)")
        self._roles = ["prefill" if i < prefill_replicas else "decode"
                       for i in range(len(engines))]
        self.telemetry = telemetry
        self.clock = clock
        self.schedulers = [
            Scheduler(e, writer, telemetry=telemetry,
                      ttft_slo_s=ttft_slo_s, clock=clock,
                      postmortem_name=None, **scheduler_kw)
            for e in engines]
        # replica health: ON by default for a real fleet (>1 replica —
        # quarantine needs survivors to requeue onto); pass a
        # HealthConfig to tune thresholds or force it for a single
        # replica, False to disable outright.
        if health is False:
            self.health: Optional[health_lib.HealthTracker] = None
        elif isinstance(health, health_lib.HealthTracker):
            self.health = health
        elif isinstance(health, health_lib.HealthConfig):
            self.health = health_lib.HealthTracker(
                len(engines), health, clock=clock)
        elif health is None and len(engines) == 1:
            self.health = None
        else:    # None with a fleet, or True
            self.health = health_lib.HealthTracker(len(engines), clock=clock)
        if telemetry is not None:
            # ONE aggregate postmortem provider for the fleet (each
            # replica's provider would collide on the name): in-flight
            # request ids + slot ages per replica, host facts only.
            telemetry.add_postmortem_provider(
                "serve_router", self.postmortem_state)
        self.ttft_slo_s = ttft_slo_s
        self._where: dict[int, tuple[int, int]] = {}
        #: front-door sheds (no routable replica): terminal records the
        #: schedulers never saw, bounded like their completed retention.
        self._router_shed: dict[int, dict] = {}
        self._shed_cap = int(scheduler_kw.get("completed_cap", 100_000))
        self._shed_router = 0
        self._requeued = 0
        #: in-flight prefill-phase handoffs: fleet rid -> (the ORIGINAL
        #: request, its submit moment). While present, the rid points at
        #: a max_new=1 prefill JOB on a prefill replica; on the job's
        #: terminal status the original request is submitted to a decode
        #: replica with the original submit_t (TTFT and deadlines honest
        #: across the handoff) and hits the pages the job just saved.
        self._handoff: dict[int, tuple[Request, float]] = {}
        self._handoffs = 0
        self._next_id = 0

    @classmethod
    def build(cls, cfg, params, *, n_replicas: int, n_slots: int,
              max_len: int, prefill_chunk: int = 16, mesh=None,
              kv_page_size: int = 0, prefix_pages: int = 0,
              page_save_after: int = 2, draft_cfg=None, draft_params=None,
              spec_k: int = 0, prefill_replicas: int = 0,
              **router_kw) -> "Router":
        """N replicas over ONE param tree. Each replica gets its own KV
        state (and page pool, when enabled) and its own AOT programs; the
        params device arrays are shared. ``draft_cfg``/``draft_params``/
        ``spec_k`` arm speculative decoding on the DECODE replicas (a
        dedicated prefill replica never decodes, so it skips the draft
        programs). ``prefill_replicas=N`` disaggregates: the first N
        replicas are prefill-role, ALL replicas mount one shared page
        store (the KV transport; saves become eager — ``save_after`` is
        forced to 1, a transport that waits for a second sighting would
        hand off nothing), and the router routes by request phase."""
        if n_replicas < 1:
            raise ValueError(f"n_replicas={n_replicas} must be >= 1")
        if prefill_replicas and not prefix_pages:
            raise ValueError(
                "prefill_replicas needs prefix_pages > 0: the page pool "
                "IS the prefill→decode KV transport")
        if prefill_replicas and not 0 < prefill_replicas < n_replicas:
            # fail BEFORE compiling N engines (the ctor re-checks)
            raise ValueError(
                f"prefill_replicas={prefill_replicas} must leave at "
                f"least one decode replica (have {n_replicas})")
        if prefill_replicas:
            page_save_after = 1
        engines, store = [], None
        for r in range(n_replicas):
            pre = r < prefill_replicas
            engines.append(DecodeEngine(
                cfg, params, n_slots=n_slots, max_len=max_len,
                prefill_chunk=prefill_chunk, mesh=mesh,
                kv_page_size=kv_page_size, prefix_pages=prefix_pages,
                page_save_after=page_save_after, shared_pages=store,
                draft_cfg=None if pre else draft_cfg,
                draft_params=None if pre else draft_params,
                spec_k=0 if pre else spec_k))
            if prefill_replicas and store is None:
                store = engines[0].page_store
        return cls(engines, prefill_replicas=prefill_replicas, **router_kw)

    # ------------------------------------------------------------ admission

    def _routable(self, i: int) -> bool:
        return self.health is None or self.health.routable(i)

    def _pick(self, phase: str = "decode") -> Optional[int]:
        """Least occupancy over ROUTABLE replicas (health rank first:
        healthy before degraded before probation); queue depth breaks the
        tie (every replica saturated → the shortest line), replica index
        breaks that (deterministic tests). With disaggregation on, only
        replicas of the request's PHASE role are candidates — unless that
        role has no routable member, in which case the whole routable
        fleet serves it (a quarantined prefill tier degrades to full
        prefill on decode replicas; it never stops the fleet). None when
        nothing at all is routable — the caller sheds at the front
        door."""
        cands = [i for i in range(len(self.schedulers)) if self._routable(i)]
        if not cands:
            return None
        if self._prefill_replicas:
            role = [i for i in cands if self._roles[i] == phase]
            cands = role or cands
        rank = (self.health.rank if self.health is not None
                else (lambda i: 0))
        return min(cands,
                   key=lambda i: (rank(i), self.schedulers[i].occupancy,
                                  self.schedulers[i].queue_depth, i))

    def _wants_prefill_replica(self, req: Request) -> bool:
        """Phase classification: a request is PREFILL-HEAVY when at least
        one full page of its prompt is not already in the shared store —
        the work a dedicated prefill replica exists to absorb. Cached
        stems and sub-page prompts go straight to decode replicas (their
        admission is one page gather + a tail chunk)."""
        if not self._prefill_replicas:
            return False
        eng = self.schedulers[0].engine
        prompt = tuple(int(t) for t in req.prompt)
        full = max(0, (len(prompt) - 1) // eng.page_size)
        if full < 1:
            return False
        have, _ = eng._prefix.longest(prompt, cap=full)
        return have < full

    def _shed_at_door(self, rid: int) -> None:
        eta = (self.health.quarantined_eta_s()
               if self.health is not None else None)
        self._router_shed[rid] = {
            "status": "shed", "tokens": [],
            "retry_after_s": round(eta if eta is not None else 1.0, 3)}
        self._where.pop(rid, None)
        self._shed_router += 1
        while len(self._router_shed) > self._shed_cap:
            self._router_shed.pop(next(iter(self._router_shed)))

    def submit(self, req: Request) -> int:
        # the fleet-global rid IS the request's trace id: every span the
        # replica scheduler and engine record for it carries this one id,
        # so a request renders end-to-end across the tiers in Perfetto.
        # Increment only after the replica ACCEPTED — a rejected submit
        # (over-long prompt) must not consume a fleet id.
        rid = self._next_id
        if self._wants_prefill_replica(req):
            i = self._pick("prefill")
            if i is None:
                self._next_id += 1
                self._shed_at_door(rid)
                return rid
            # the PREFILL JOB: same prompt/sampling/deadlines, one token —
            # its whole value is the page-save side effect. The original
            # request rides self._handoff until the job is terminal.
            t0 = self.clock()
            job = dataclasses.replace(req, max_new=1)
            local = self.schedulers[i].submit(job, trace_id=rid,
                                              submit_t=t0)
            self._next_id += 1
            self._where[rid] = (i, local)
            self._handoff[rid] = (req, t0)
            self._handoffs += 1
            return rid
        i = self._pick()
        if i is None:
            # nothing routable: shed at the front door with the earliest
            # probation ETA as the honest retry hint
            self._next_id += 1
            self._shed_at_door(rid)
            return rid
        local = self.schedulers[i].submit(req, trace_id=rid)
        self._next_id += 1
        self._where[rid] = (i, local)
        return rid

    def _promote_handoffs(self) -> None:
        """Move every finished prefill job's ORIGINAL request onto a
        decode replica. ``done`` promotes (the pages are saved; the
        decode admission gathers them) and so does ``shed`` (the prefill
        queue was full — the decode tier may still have room, where the
        request prefills from scratch). A ``timeout``/``error`` job is
        ADOPTED as the request's own verdict instead: the deadline was
        measured from the original submit and a poisoned prefill raises
        wherever it lands, so a decode-side replay could only repeat the
        same outcome while double-counting it in fleet stats — poll()
        keeps reading the job's terminal record through ``_where``."""
        if not self._handoff:
            return
        for rid in list(self._handoff):
            i, local = self._where[rid]
            st = self.schedulers[i].poll(local)
            if st["status"] not in ("done",) + FAILED_STATUSES:
                continue
            req, t0 = self._handoff.pop(rid)
            if st["status"] in ("timeout", "error"):
                continue               # adopted verdict; record retained
            self.schedulers[i].release(local)   # drop a DONE job's record
            j = self._pick()
            if j is None:
                self._shed_at_door(rid)
                continue
            local2 = self.schedulers[j].submit(req, trace_id=rid,
                                               submit_t=t0)
            self._where[rid] = (j, local2)

    def replica_of(self, rid: int) -> int:
        """Which replica holds request ``rid`` (admission audit)."""
        return self._where[rid][0]

    def postmortem_state(self) -> dict:
        """Fleet postmortem context: per-replica in-flight request ids,
        slot ages and health verdicts (host facts only — the
        flight-recorder dump contract)."""
        out = {f"replica{i}": s.postmortem_state()
               for i, s in enumerate(self.schedulers)}
        out["router"] = {"shed_at_door": self._shed_router,
                         "requeued": self._requeued}
        if self.health is not None:
            out["router"]["health"] = self.health.states()
            out["router"]["health_counters"] = dict(self.health.counters)
            out["router"]["health_transitions"] = \
                list(self.health.transitions)[-10:]
        return out

    # ------------------------------------------------------ quarantine drain

    def quarantine(self, i: int, cause: str = "forced") -> None:
        """Quarantine replica ``i`` now and requeue its in-flight
        requests onto survivors (operator/test API; the health watchdog
        reaches the same path through :meth:`tick`'s verdicts)."""
        if self.health is None:
            raise RuntimeError(
                "Router health is disabled (single replica without an "
                "explicit HealthConfig) — nothing to quarantine with")
        self.health.quarantine(i, cause)
        self._requeue_from(i)

    def _requeue_from(self, i: int) -> None:
        """Drain quarantined replica ``i``: every in-flight request is
        re-submitted to a survivor in submit order with its ORIGINAL
        fleet rid, trace id and submit time — the survivor re-prefills
        (cached stems in one page gather where its prefix pool has them)
        and regenerates the deterministic token stream, so completed
        tokens are bitwise identical to a fault-free run. With no
        routable survivor the request sheds at the front door."""
        for rec in self.schedulers[i].evict_for_requeue():
            rid = rec.trace_id     # the fleet-global id (we threaded it)
            # a drained prefill JOB stays in its phase: re-route it to a
            # surviving prefill replica (or, via _pick's role fallback,
            # anywhere routable when the whole prefill tier is down)
            phase = "prefill" if rid in self._handoff else "decode"
            j = self._pick(phase)  # never i: quarantined is not routable
            if j is None:
                self._handoff.pop(rid, None)
                self._shed_at_door(rid)
                continue
            local = self.schedulers[j].submit(
                rec.req, trace_id=rid, submit_t=rec.submit_t, requeued=True)
            self._where[rid] = (j, local)
            self._requeued += 1

    def _probe(self, i: int) -> None:
        """Exercise an idle probation replica with one timed decode probe
        so re-admission does not have to wait for (and gamble) live
        traffic. Engines without a ``probe`` (fakes) skip — their
        probation resolves through routed requests instead."""
        probe = getattr(self.schedulers[i].engine, "probe", None)
        if probe is None:
            return
        t0 = self.clock()
        try:
            probe()
        except Exception as e:  # noqa: BLE001 — a probe failure is the
            # quarantine signal working; nothing to requeue (idle replica)
            self.health.note_fault(i, e)
            return
        self.health.note_tick(i, self.clock() - t0)

    # ----------------------------------------------------------- pump surface

    @property
    def pending(self) -> int:
        return sum(s.pending for s in self.schedulers)

    def tick(self) -> None:
        """One scheduling round on every ROUTABLE replica with work —
        replicas are independent KV state, so their ticks never contend
        for slots. With health on, each tick is wall-timed and fed to the
        watchdog; a quarantine verdict (slow/wedged/faulted) immediately
        drains that replica onto survivors, so the pump loop never calls
        into a wedged engine again."""
        h = self.health
        if h is None:
            for s in self.schedulers:
                if s.pending:
                    s.tick()
            self._promote_handoffs()
            return
        for i, s in enumerate(self.schedulers):
            if not h.routable(i):
                continue
            if not s.pending:
                if h.state(i) == health_lib.PROBATION:
                    self._probe(i)
                continue
            t0 = self.clock()
            try:
                s.tick()
            except Exception as e:  # noqa: BLE001 — a decode-path engine
                # failure has no single owning request: quarantine the
                # replica and replay its in-flight work on survivors
                h.note_fault(i, e)
                self._requeue_from(i)
                continue
            if h.note_tick(i, self.clock() - t0) == health_lib.QUARANTINED:
                self._requeue_from(i)
        self._promote_handoffs()

    def run_until_idle(self, max_ticks: int = 100000, *,
                       on_tick=None) -> None:
        for _ in range(max_ticks):
            if not self.pending:
                return
            self.tick()
            if on_tick is not None:
                on_tick()
        raise RuntimeError(f"requests still pending after {max_ticks} ticks")

    def poll(self, rid: int) -> dict:
        shed = self._router_shed.get(rid)
        if shed is not None:
            return dict(shed)
        if rid in self._handoff:
            # prefill phase of a disaggregated request: the job's local
            # statuses (and its one sampled token) are plumbing — the
            # caller sees a request that is still prefilling
            return {"status": "prefill", "tokens": []}
        i, local = self._where[rid]
        return self.schedulers[i].poll(local)

    def result(self, rid: int, max_ticks: int = 100000) -> list[int]:
        for _ in range(max_ticks):
            st = self.poll(rid)
            if st["status"] == "done":
                return st["tokens"]
            if st["status"] in FAILED_STATUSES:
                # shed/timeout/error are TERMINAL: raise now instead of
                # pumping max_ticks on a request that will never finish
                raise RequestFailed(rid, st)
            self.tick()
        raise RuntimeError(f"request {rid} not done after {max_ticks} ticks")

    def release(self, rid: int) -> None:
        if self._router_shed.pop(rid, None) is not None:
            return
        i, local = self._where.pop(rid)
        self.schedulers[i].release(local)

    def drain(self) -> None:
        self.run_until_idle()

    # --------------------------------------------------------------- metrics

    def trace_counts(self) -> list[dict]:
        """Per-replica program trace counters (page fences merged in) —
        the steady-state recompile pin, fleet edition."""
        return [{**s.engine.trace_counts,
                 **{f"page_{k}": v
                    for k, v in s.engine.page_trace_counts.items()}}
                for s in self.schedulers]

    def stats(self, brief: bool = False) -> dict:
        """Fleet aggregates + the ``replica{i}_*`` SLO panel."""
        n = len(self.schedulers)
        out = {
            "router_replicas": float(n),
            "router_completed": float(sum(s._completed
                                          for s in self.schedulers)),
            "router_queue_depth": float(sum(s.queue_depth
                                            for s in self.schedulers)),
            "router_occupancy": (sum(s.occupancy for s in self.schedulers)
                                 / n),
        }
        if brief:
            return out
        out["router_shed"] = float(self._shed_router
                                   + sum(s._shed for s in self.schedulers))
        out["router_timeouts"] = float(sum(s._timeouts
                                           for s in self.schedulers))
        out["router_request_errors"] = float(
            sum(s._request_errors for s in self.schedulers))
        out["router_requeued"] = float(self._requeued)
        if self._prefill_replicas:
            out["router_prefill_replicas"] = float(self._prefill_replicas)
            out["router_handoffs"] = float(self._handoffs)
            for i, role in enumerate(self._roles):
                out[f"replica{i}_role"] = role
        if self.health is not None:
            hc = self.health.counters
            out["router_quarantines"] = float(hc["quarantines"])
            out["router_probation_readmits"] = float(hc["readmits"])
            out["router_replica_faults"] = float(hc["faults"])
            for i in range(n):
                out[f"replica{i}_health"] = self.health.state(i)
        # fleet TTFT: with disaggregation on, prefill-role schedulers'
        # samples are JOB latencies (plumbing), not user-visible first
        # tokens — the decode replicas record the real TTFT (measured
        # from the ORIGINAL submit via the threaded submit_t)
        ttfts = [t for i, s in enumerate(self.schedulers)
                 if not (self._prefill_replicas
                         and self._roles[i] == "prefill")
                 for t in s._ttfts]
        out["router_ttft_p50_s"] = _quantile(ttfts, 0.5)
        out["router_ttft_p99_s"] = _quantile(ttfts, 0.99)
        if self.ttft_slo_s > 0.0:
            out["router_ttft_slo_ok_frac"] = (
                sum(1 for t in ttfts if t <= self.ttft_slo_s) / len(ttfts)
                if ttfts else 1.0)
        # fleet-summed engine counters (prefill chunks, page hits, ...)
        counters: dict = {}
        for s in self.schedulers:
            for k, v in getattr(s.engine, "counters", {}).items():
                counters[k] = counters.get(k, 0) + v
        out.update({f"router_{k}": float(v) for k, v in counters.items()})
        for i, s in enumerate(self.schedulers):
            st = s.stats()
            for k in _REPLICA_KEYS:
                if k in st:
                    out[f"replica{i}_{k}"] = st[k]
        if self.telemetry is not None:
            roll = self.telemetry.spans.rollup().get("router_wait")
            if roll is not None:
                out["router_wait_p50_s"] = roll["p50_s"]
                out["router_wait_p99_s"] = roll["p99_s"]
        return out


def poisson_replay(router, arrivals, *, clock=time.perf_counter,
                   sleep=time.sleep) -> float:
    """:func:`dtf_tpu.serve.client.replay` works unchanged on a Router
    (same submit/tick/pending surface) — re-exported here so fleet benches
    read naturally."""
    from dtf_tpu.serve.client import replay

    return replay(router, arrivals, clock=clock, sleep=sleep)


__all__ = ["Router", "poisson_replay"]
