"""Online inference: continuous-batching decode over the GPT flagship.

The training side of the framework has carried every PR so far; this
package is the serving side the ROADMAP north star ("serves heavy traffic
from millions of users") actually asks for. Five layers:

- :mod:`dtf_tpu.serve.engine` — ``DecodeEngine``: KV cache + per-slot
  positions/rng/sampling-params as persistent sharded device state, with
  exactly TWO AOT-compiled fixed-shape programs (``prefill_into_slot``,
  ``decode_all``) — or exactly FOUR with speculative decoding armed
  (``prefill``, ``decode/verify``, ``draft_prefill``, ``draft_all``: a
  small draft model proposes k tokens per slot per tick, the verifier
  scores all k+1 positions in one masked pass, token streams identical
  to plain decode) — plus an optional prefix page pool with its own
  ``page_save``/``page_load`` pair. Zero steady-state recompiles by
  construction.
- :mod:`dtf_tpu.serve.pages` — the block-granular prefix KV cache:
  fixed-size pages with refcounts and LRU eviction, keyed by token-hash
  with exact-match verification, so shared prompt stems prefill once.
- :mod:`dtf_tpu.serve.scheduler` — request queue, FIFO admission with
  prefill/page-load/decode interleave, slot allocation, EOS/max-len
  eviction, and TTFT / per-token-latency / queue-depth / occupancy /
  SLO metrics.
- :mod:`dtf_tpu.serve.router` — ``Router``: N engine replicas (one shared
  param tree, independent KV state) behind least-occupancy admission with
  queue-depth tiebreak, ``router_wait`` spans and per-replica SLO
  rollups. With ``prefill_replicas=N`` the fleet DISAGGREGATES: dedicated
  prefill replicas absorb long-prompt work and hand the KV off through a
  shared page store (``PageStore`` — the pool as transport) to decode
  replicas, and admission routes by request phase instead of occupancy
  alone.
- :mod:`dtf_tpu.serve.client` — in-process submit/poll API plus a seeded
  Poisson load generator for benching.
- :mod:`dtf_tpu.serve.health` — the resilience tier (ISSUE 12): a
  per-replica health state machine (healthy → degraded → quarantined →
  probation) on the PR 11 stall-watchdog idiom, plus serve-side fault
  injection (``DTF_FAULT_INJECT=wedge_replica@... | slow_decode |
  poison_request``). Pairs with per-request deadlines, bounded-queue
  load shedding and quarantine requeue in scheduler/router.

Above the router sits the zero-downtime WEIGHT HOT-SWAP (ISSUE 14):
``Router.start_swap`` rolls newly published param versions
(:mod:`dtf_tpu.publish` — atomic versioned manifests) across the fleet
one drained replica at a time with a health-gated canary and automatic
fleet-wide rollback; completed records are stamped with the param
version that decoded them and prefix pages are version-epoch'd so
cached KV never crosses a swap.

docs/SERVING.md walks the architecture and the fixed-shape rules;
docs/RESILIENCE.md "Serving" + §9 walk the failure semantics.
"""

from dtf_tpu.serve.client import (Heartbeat, PoissonLoadGen, ServeClient,
                                  replay)
from dtf_tpu.serve.engine import DecodeEngine, decode_step_view
from dtf_tpu.serve.health import (HealthConfig, HealthTracker,
                                  install_serve_fault)
from dtf_tpu.serve.pages import PageStore, PrefixIndex
from dtf_tpu.serve.router import Router, SwapConfig
from dtf_tpu.serve.scheduler import (FAILED_STATUSES, Request,
                                     RequestFailed, Scheduler)

__all__ = ["DecodeEngine", "FAILED_STATUSES", "Heartbeat", "HealthConfig",
           "HealthTracker", "PageStore", "PoissonLoadGen", "PrefixIndex",
           "Request", "RequestFailed", "Router", "Scheduler", "ServeClient",
           "SwapConfig", "decode_step_view", "install_serve_fault",
           "replay"]
