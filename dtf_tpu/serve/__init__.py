"""Online inference: continuous-batching decode over the GPT flagship.

The training side of the framework has carried every PR so far; this
package is the serving side the ROADMAP north star ("serves heavy traffic
from millions of users") actually asks for. Three layers:

- :mod:`dtf_tpu.serve.engine` — ``DecodeEngine``: KV cache + per-slot
  positions/rng/sampling-params as persistent sharded device state, with
  exactly TWO AOT-compiled fixed-shape programs (``prefill_into_slot``,
  ``decode_all``). Zero steady-state recompiles by construction.
- :mod:`dtf_tpu.serve.scheduler` — request queue, FIFO admission with
  prefill/decode interleave, slot allocation, EOS/max-len eviction, and
  TTFT / per-token-latency / queue-depth / occupancy metrics.
- :mod:`dtf_tpu.serve.client` — in-process submit/poll API plus a seeded
  Poisson load generator for benching.

docs/SERVING.md walks the architecture and the fixed-shape rules.
"""

from dtf_tpu.serve.client import PoissonLoadGen, ServeClient, replay
from dtf_tpu.serve.engine import DecodeEngine, decode_step_view
from dtf_tpu.serve.scheduler import Request, Scheduler

__all__ = ["DecodeEngine", "PoissonLoadGen", "Request", "Scheduler",
           "ServeClient", "decode_step_view", "replay"]
