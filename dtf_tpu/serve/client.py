"""In-process serving client + seeded Poisson load generator.

``ServeClient`` is the submit/poll surface a caller (or the reference-style
launcher ``scripts/serve_gpt.py``) talks to — it owns a
:class:`~dtf_tpu.serve.scheduler.Scheduler` and pumps it. ``PoissonLoadGen``
produces a reproducible open-loop arrival process (exponential
inter-arrivals, seeded prompt/length sampling) for benching: the A/B
against static batched ``generate()`` rides
``scripts/bench_decode.py --sweep-serve``.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import sys
import time
from typing import Iterator, Optional, Sequence

import numpy as np

from dtf_tpu.serve.scheduler import (FAILED_STATUSES, Request,
                                     RequestFailed, Scheduler)

log = logging.getLogger("dtf_tpu")


def replay(scheduler: Scheduler, arrivals, *,
           clock=time.perf_counter, sleep=time.sleep,
           on_tick=None) -> float:
    """Open-loop arrival replay: submit each ``(t_arrival, Request)`` when
    its wall-clock moment comes, tick the scheduler whenever work is
    pending, and drain. Returns the makespan in seconds. THE one pump loop
    — serve_gpt.py and the bench A/B both drive it, so admission timing
    cannot drift between them. Returns request ids in submit order via
    ``scheduler`` (callers poll). ``on_tick`` (optional, zero-arg) fires
    after every scheduler tick — the :class:`Heartbeat` hook point."""
    arrivals = list(arrivals)
    t0 = clock()
    i = 0
    while i < len(arrivals) or scheduler.pending:
        now = clock() - t0
        while i < len(arrivals) and arrivals[i][0] <= now:
            scheduler.submit(arrivals[i][1])
            i += 1
        if scheduler.pending:
            scheduler.tick()
            if on_tick is not None:
                on_tick()
        elif i < len(arrivals):
            sleep(min(arrivals[i][0] - now, 0.05))
    return clock() - t0


#: heartbeat snapshot keys, in emit order — the operator's at-a-glance
#: panel (everything else stays in the final stats() line).
_HEARTBEAT_KEYS = ("serve_completed", "serve_queue_depth",
                   "serve_occupancy", "serve_ttft_p50_s",
                   "serve_ttft_p99_s", "serve_ttft_slo_ok_frac",
                   "serve_shed", "serve_timeouts",
                   "router_completed", "router_queue_depth",
                   "router_occupancy", "router_ttft_p50_s",
                   "router_ttft_p99_s", "router_ttft_slo_ok_frac",
                   "router_shed", "router_timeouts", "router_requeued",
                   "router_quarantines", "router_version",
                   "router_swaps", "router_swap_rollbacks",
                   "router_swap_in_progress")


class Heartbeat:
    """Periodic one-line JSON liveness snapshots of a running server.

    Call :meth:`maybe_emit` after every scheduler/router tick (``replay``'s
    ``on_tick``, or the explicit pump loop): every ``every_ticks`` ticks it
    emits one ``{"serve_heartbeat": ...}`` JSON line via ``emit`` (default:
    stderr — stdout's LAST line stays the launcher's one metrics line) with
    the scheduler/router ``stats()`` panel: per-replica occupancy, TTFT
    p50/p99, and the SLO compliance fraction. When ``slo_floor > 0`` and
    the ok-fraction drops below it, a WARNING logs once per excursion
    EPISODE — per key: the fleet aggregate and each ``replica{i}`` panel
    dedup independently, re-armed when that key's compliance recovers (a
    sustained breach, or one breach seen through several replicas, must
    not spam one warning per tick); every excursion is COUNTED and the
    worst ok-fraction retained, so :meth:`stats` can stamp both into the
    launcher's final JSON line (a run that breached and recovered is not
    allowed to look clean). With an ``events`` log attached, each episode
    lands on the run timeline as paired ``slo_excursion`` enter/exit
    records carrying their entry/exit ticks. With a ``flight`` recorder attached, each
    emit also writes the atomic liveness heartbeat file with a ``serve``
    summary — the PR 11 run-controller surface, serving edition. Host
    arithmetic only; stats() is already readback-free.
    """

    def __init__(self, sched, *, every_ticks: int, slo_floor: float = 0.0,
                 emit=None, clock=time.monotonic, flight=None, events=None):
        if every_ticks < 1:
            raise ValueError(f"every_ticks={every_ticks} must be >= 1")
        self.sched = sched
        self.every_ticks = every_ticks
        self.slo_floor = slo_floor
        self.emit = emit or (lambda line: print(line, file=sys.stderr))
        self.clock = clock
        self.flight = flight
        #: optional fleet EventLog (ISSUE 20): excursion entry/exit edges
        #: land on the run timeline with their ticks
        self.events = events
        self._t0 = clock()
        self._ticks = 0
        self.emitted = 0
        self.excursions = 0
        self.replica_excursions = 0
        self.worst_ok_frac: float | None = None
        #: open excursion episodes, keyed "fleet" / "replica{i}" — entry
        #: is the ONE moment that WARNs and emits (a sustained breach, or
        #: the same breach seen through several replicas' panels, must
        #: not spam); exit closes the episode on the event plane.
        self._episodes: dict = {}

    def snapshot(self) -> dict:
        stats = self.sched.stats()
        snap = {"serve_heartbeat": self.emitted,
                "t_s": round(self.clock() - self._t0, 3)}
        for k in _HEARTBEAT_KEYS:
            if k in stats:
                snap[k] = (round(v, 6) if isinstance(v := stats[k], float)
                           else v)
        # the per-replica SLO panel (Router stats) rides along verbatim
        for k, v in stats.items():
            if k.startswith("replica"):
                snap[k] = round(v, 6) if isinstance(v, float) else v
        return snap

    def _slo_ok_frac(self, snap) -> float | None:
        for k in ("router_ttft_slo_ok_frac", "serve_ttft_slo_ok_frac"):
            if k in snap:
                return snap[k]
        return None

    def maybe_emit(self) -> dict | None:
        self._ticks += 1
        if self._ticks % self.every_ticks:
            return None
        snap = self.snapshot()
        self.emitted += 1
        self.emit(json.dumps(snap))
        ok = self._slo_ok_frac(snap)
        if ok is not None:
            self.worst_ok_frac = (ok if self.worst_ok_frac is None
                                  else min(self.worst_ok_frac, ok))
        if self.slo_floor > 0.0:
            fracs = {}
            if ok is not None:
                fracs["fleet"] = ok
            suffix = "_serve_ttft_slo_ok_frac"
            for k, v in snap.items():
                if k.startswith("replica") and k.endswith(suffix):
                    fracs[k[:-len(suffix)]] = v
            for key, frac in fracs.items():
                ep = self._episodes.get(key)
                if frac < self.slo_floor and ep is None:
                    self._episodes[key] = {"tick": self._ticks,
                                           "ok": frac}
                    if key == "fleet":
                        self.excursions += 1
                        log.warning(
                            "TTFT SLO compliance %.3f below the %.3f "
                            "floor (p99 %.4fs; excursion %d)", frac,
                            self.slo_floor,
                            snap.get("router_ttft_p99_s",
                                     snap.get("serve_ttft_p99_s", 0.0)),
                            self.excursions)
                    else:
                        self.replica_excursions += 1
                        log.warning(
                            "%s TTFT SLO compliance %.3f below the %.3f "
                            "floor (one WARN per replica episode)",
                            key, frac, self.slo_floor)
                    if self.events is not None:
                        self.events.emit(
                            "slo_excursion", edge="enter", key=key,
                            ok_frac=round(frac, 6), tick=self._ticks)
                elif frac >= self.slo_floor and ep is not None:
                    del self._episodes[key]
                    if self.events is not None:
                        self.events.emit(
                            "slo_excursion", edge="exit", key=key,
                            ok_frac=round(frac, 6), tick=self._ticks,
                            entered_tick=ep["tick"],
                            ticks=self._ticks - ep["tick"])
        if self.flight is not None:
            # the run-controller liveness surface: the heartbeat file a
            # chief-side watcher polls, with the serve panel riding along
            serve = {k: snap[k] for k in
                     ("serve_completed", "serve_queue_depth",
                      "router_completed", "router_queue_depth",
                      "router_quarantines", "router_version",
                      "router_swaps", "router_swap_rollbacks")
                     if k in snap}
            # per-replica ACTIVE param versions: the flight-recorder
            # serve panel's skew view (ISSUE 14 satellite)
            versions = {k: snap[k] for k in snap
                        if k.startswith("replica") and k.endswith("_version")}
            if versions:
                serve["replica_versions"] = versions
            self.flight.write_heartbeat(extra={"serve": serve})
        return snap

    def stats(self) -> dict:
        """SLO-excursion aggregates for the launcher's final JSON line:
        how often compliance dipped below the floor and how bad the worst
        dip was (a breach-and-recover run must not look clean)."""
        out = {"heartbeats": float(self.emitted),
               "slo_excursions": float(self.excursions),
               "replica_slo_excursions": float(self.replica_excursions)}
        if self.worst_ok_frac is not None:
            out["worst_ttft_slo_ok_frac"] = round(self.worst_ok_frac, 6)
        return out


class ServeClient:
    """Submit/poll API over an engine. ``submit`` returns a request id;
    ``result`` blocks (pumping the scheduler) until that request is done."""

    def __init__(self, engine, writer=None, **scheduler_kw):
        self.scheduler = Scheduler(engine, writer, **scheduler_kw)

    def submit(self, prompt: Sequence[int], *, max_new: int = 32,
               temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
               eos_id: Optional[int] = None, pad_id: int = 0,
               seed: int = 0) -> int:
        return self.scheduler.submit(Request(
            prompt=list(prompt), max_new=max_new, temperature=temperature,
            top_k=top_k, top_p=top_p, eos_id=eos_id, pad_id=pad_id,
            seed=seed))

    def poll(self, rid: int) -> dict:
        return self.scheduler.poll(rid)

    def step(self) -> None:
        self.scheduler.tick()

    def result(self, rid: int, max_ticks: int = 100000) -> list[int]:
        """Generated tokens of ``rid`` (pumps the scheduler until done).
        A shed/timed-out/errored request raises :class:`RequestFailed`
        IMMEDIATELY — terminal statuses must not spin ``max_ticks`` to
        exhaustion on a request that will never finish."""
        for _ in range(max_ticks):
            st = self.poll(rid)
            if st["status"] == "done":
                return st["tokens"]
            if st["status"] in FAILED_STATUSES:
                raise RequestFailed(rid, st)
            self.scheduler.tick()
        raise RuntimeError(f"request {rid} not done after {max_ticks} ticks")

    def drain(self) -> None:
        self.scheduler.run_until_idle()

    def stats(self) -> dict:
        return self.scheduler.stats()


@dataclasses.dataclass(frozen=True)
class PoissonLoadGen:
    """Seeded open-loop load: ``arrivals()`` yields ``(t_arrival, Request)``
    with Exp(rate) inter-arrival gaps, prompts of uniform random length in
    ``[prompt_min, prompt_max]`` over ``vocab_size`` tokens, and ``max_new``
    uniform in ``[new_min, new_max]`` — the mixed-length churn continuous
    batching exists for. Deterministic per seed (benches commit rows)."""

    rate: float                       # requests per second
    n_requests: int
    vocab_size: int
    prompt_min: int = 4
    prompt_max: int = 64
    new_min: int = 8
    new_max: int = 64
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_id: Optional[int] = None
    seed: int = 0

    def __post_init__(self):
        # fail at construction, not mid-replay inside numpy
        if self.rate <= 0:
            raise ValueError(f"rate={self.rate} must be > 0")
        if not 1 <= self.prompt_min <= self.prompt_max:
            raise ValueError(
                f"need 1 <= prompt_min ({self.prompt_min}) <= prompt_max "
                f"({self.prompt_max})")
        if not 1 <= self.new_min <= self.new_max:
            raise ValueError(
                f"need 1 <= new_min ({self.new_min}) <= new_max "
                f"({self.new_max})")

    def arrivals(self) -> Iterator[tuple[float, Request]]:
        rng = np.random.default_rng(self.seed)
        t = 0.0
        for i in range(self.n_requests):
            t += float(rng.exponential(1.0 / self.rate))
            n_p = int(rng.integers(self.prompt_min, self.prompt_max + 1))
            prompt = rng.integers(0, self.vocab_size, n_p).tolist()
            yield t, Request(
                prompt=prompt,
                max_new=int(rng.integers(self.new_min, self.new_max + 1)),
                temperature=self.temperature, top_k=self.top_k,
                top_p=self.top_p, eos_id=self.eos_id, seed=self.seed + i)
