"""In-process serving client + seeded Poisson load generator.

``ServeClient`` is the submit/poll surface a caller (or the reference-style
launcher ``scripts/serve_gpt.py``) talks to — it owns a
:class:`~dtf_tpu.serve.scheduler.Scheduler` and pumps it. ``PoissonLoadGen``
produces a reproducible open-loop arrival process (exponential
inter-arrivals, seeded prompt/length sampling) for benching: the A/B
against static batched ``generate()`` rides
``scripts/bench_decode.py --sweep-serve``.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Iterator, Optional, Sequence

import numpy as np

from dtf_tpu.serve.scheduler import Request, Scheduler


def replay(scheduler: Scheduler, arrivals, *,
           clock=time.perf_counter, sleep=time.sleep) -> float:
    """Open-loop arrival replay: submit each ``(t_arrival, Request)`` when
    its wall-clock moment comes, tick the scheduler whenever work is
    pending, and drain. Returns the makespan in seconds. THE one pump loop
    — serve_gpt.py and the bench A/B both drive it, so admission timing
    cannot drift between them. Returns request ids in submit order via
    ``scheduler`` (callers poll)."""
    arrivals = list(arrivals)
    t0 = clock()
    i = 0
    while i < len(arrivals) or scheduler.pending:
        now = clock() - t0
        while i < len(arrivals) and arrivals[i][0] <= now:
            scheduler.submit(arrivals[i][1])
            i += 1
        if scheduler.pending:
            scheduler.tick()
        elif i < len(arrivals):
            sleep(min(arrivals[i][0] - now, 0.05))
    return clock() - t0


class ServeClient:
    """Submit/poll API over an engine. ``submit`` returns a request id;
    ``result`` blocks (pumping the scheduler) until that request is done."""

    def __init__(self, engine, writer=None, **scheduler_kw):
        self.scheduler = Scheduler(engine, writer, **scheduler_kw)

    def submit(self, prompt: Sequence[int], *, max_new: int = 32,
               temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
               eos_id: Optional[int] = None, pad_id: int = 0,
               seed: int = 0) -> int:
        return self.scheduler.submit(Request(
            prompt=list(prompt), max_new=max_new, temperature=temperature,
            top_k=top_k, top_p=top_p, eos_id=eos_id, pad_id=pad_id,
            seed=seed))

    def poll(self, rid: int) -> dict:
        return self.scheduler.poll(rid)

    def step(self) -> None:
        self.scheduler.tick()

    def result(self, rid: int, max_ticks: int = 100000) -> list[int]:
        """Generated tokens of ``rid`` (pumps the scheduler until done)."""
        for _ in range(max_ticks):
            st = self.poll(rid)
            if st["status"] == "done":
                return st["tokens"]
            self.scheduler.tick()
        raise RuntimeError(f"request {rid} not done after {max_ticks} ticks")

    def drain(self) -> None:
        self.scheduler.run_until_idle()

    def stats(self) -> dict:
        return self.scheduler.stats()


@dataclasses.dataclass(frozen=True)
class PoissonLoadGen:
    """Seeded open-loop load: ``arrivals()`` yields ``(t_arrival, Request)``
    with Exp(rate) inter-arrival gaps, prompts of uniform random length in
    ``[prompt_min, prompt_max]`` over ``vocab_size`` tokens, and ``max_new``
    uniform in ``[new_min, new_max]`` — the mixed-length churn continuous
    batching exists for. Deterministic per seed (benches commit rows)."""

    rate: float                       # requests per second
    n_requests: int
    vocab_size: int
    prompt_min: int = 4
    prompt_max: int = 64
    new_min: int = 8
    new_max: int = 64
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_id: Optional[int] = None
    seed: int = 0

    def __post_init__(self):
        # fail at construction, not mid-replay inside numpy
        if self.rate <= 0:
            raise ValueError(f"rate={self.rate} must be > 0")
        if not 1 <= self.prompt_min <= self.prompt_max:
            raise ValueError(
                f"need 1 <= prompt_min ({self.prompt_min}) <= prompt_max "
                f"({self.prompt_max})")
        if not 1 <= self.new_min <= self.new_max:
            raise ValueError(
                f"need 1 <= new_min ({self.new_min}) <= new_max "
                f"({self.new_max})")

    def arrivals(self) -> Iterator[tuple[float, Request]]:
        rng = np.random.default_rng(self.seed)
        t = 0.0
        for i in range(self.n_requests):
            t += float(rng.exponential(1.0 / self.rate))
            n_p = int(rng.integers(self.prompt_min, self.prompt_max + 1))
            prompt = rng.integers(0, self.vocab_size, n_p).tolist()
            yield t, Request(
                prompt=prompt,
                max_new=int(rng.integers(self.new_min, self.new_max + 1)),
                temperature=self.temperature, top_k=self.top_k,
                top_p=self.top_p, eos_id=self.eos_id, seed=self.seed + i)
