"""Serve-log sink — terminal requests recorded as future training data.

The write half of the flywheel (ISSUE 19; the read half is
:class:`dtf_tpu.data.stream.servelog.ServeLogSource`): a scheduler/router
attachment that records every terminal ``done`` request — prompt +
completion token ids, the param version that decoded it, per-request spec
acceptance counts, TTFT/latency, replica id — into size-rotated jsonl
shards under one sink directory, framed by the shared record codec
(per-record CRC32C).

Durability contract (the publish-manifest discipline applied to traffic):

- every byte goes through the ``_hostio`` choke points — records append
  via :func:`~dtf_tpu._hostio.append_line` (single-writer jsonl; the
  serve pump is one process), the manifest commits via
  :func:`~dtf_tpu._hostio.atomic_replace`;
- a shard enters the manifest only when ROTATED (or flushed/closed) —
  the manifest is the atomic commit point, so a crash mid-rotation
  (``crash_in_log_rotate`` chaos verb) leaves the fully-written shard on
  disk and the next sink over the directory ADOPTS it back into the
  manifest: committed records are never lost, never re-ordered, and the
  adopted shard keeps its name (orphan shard names are never reused);
- zero added device readbacks: every recorded fact is a host int/float
  the scheduler already holds (token ids cross the device boundary once,
  in the decode tick's existing ``int()`` conversions — the PR 5 idiom).

All values recorded are HOST facts handed in by the scheduler — the sink
itself never touches a clock, an rng, or a device. jax-free at module
level: ``dtf_tpu.serve.__init__`` pulls the engine (and jax), so import
this module directly (``dtf_tpu.serve.logsink``) from no-backend
contexts; srclint fences its import list like ``fault/``+``data/stream``.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Optional

from dtf_tpu._hostio import append_line, atomic_replace
from dtf_tpu.fault.inject import InjectedCrash
from dtf_tpu.data.stream.servelog import (MANIFEST_VERSION, decode_record,
                                          encode_record, manifest_path,
                                          read_manifest, shard_name)

log = logging.getLogger("dtf_tpu")


class LogSink:
    """Size-rotated serve-log writer over one sink directory.

    ``rotate_bytes`` bounds a shard's payload (the check runs after each
    append, so one oversized record still lands whole); ``0`` disables
    rotation — everything rides one shard committed at :meth:`flush`/
    :meth:`close`. One sink per directory per process (the ``append_line``
    single-writer contract); a Router's replicas SHARE one sink — the
    pump is one thread, and records carry their replica id.
    """

    def __init__(self, sink_dir: str, *, rotate_bytes: int = 1 << 20,
                 events=None):
        self.dir = os.fspath(sink_dir)
        self.rotate_bytes = int(rotate_bytes)
        #: optional fleet EventLog (ISSUE 20): rotations and orphan
        #: adoptions land on the run timeline (set BEFORE adoption so a
        #: recovery at mount is itself on the record).
        self.events = events
        manifest = read_manifest(self.dir)
        self._shards: list = list(manifest["shards"]) if manifest else []
        self._adopted = self._adopt_orphans()
        #: the OPEN shard: next index after every shard on disk —
        #: committed or orphaned — so a crashed rotation's name is never
        #: reused (two generations of records must never interleave).
        self._shard_index = self._next_index()
        self._open_records = 0
        self._open_bytes = 0
        self._records = 0
        self._rotations = 0
        #: chaos seams (install_serve_fault): damage the CRC of the N-th
        #: record written / crash after the N-th rotation's shard is
        #: durable but BEFORE its manifest commit.
        self._corrupt_at: Optional[int] = None
        self._crash_rotate_at: Optional[int] = None
        self._fault_note = None
        self._injected_corrupt = 0

    # ----------------------------------------------------------- recovery

    def _adopt_orphans(self) -> int:
        """Fold fully-written shards a crashed rotation left uncommitted
        back into the manifest (module docstring). Record counts are
        re-derived from the shard's CRC-valid lines."""
        try:
            on_disk = sorted(n for n in os.listdir(self.dir)
                             if n.startswith("shard-")
                             and n.endswith(".jsonl"))
        except FileNotFoundError:
            return 0
        committed = {s["name"] for s in self._shards}
        adopted = 0
        for name in on_disk:
            if name in committed:
                continue
            n = self._count_records(os.path.join(self.dir, name))
            self._shards.append({"name": name, "records": n})
            adopted += 1
            log.warning(
                "serve-log sink %s: adopted orphan shard %s (%d records) "
                "— a previous sink crashed between the shard write and "
                "its manifest commit; committed records are never lost",
                self.dir, name, n)
            if self.events is not None:
                self.events.emit("logsink_adopt", shard=name, records=n)
        if adopted:
            self._shards.sort(key=lambda s: s["name"])
            self._commit_manifest()
        return adopted

    @staticmethod
    def _count_records(path: str) -> int:
        with open(path) as f:
            return sum(1 for line in f.read().split("\n")
                       if line and decode_record(line) is not None)

    def _next_index(self) -> int:
        try:
            on_disk = [n for n in os.listdir(self.dir)
                       if n.startswith("shard-") and n.endswith(".jsonl")]
        except FileNotFoundError:
            on_disk = []
        idx = [int(n[len("shard-"):-len(".jsonl")]) for n in on_disk
               if n[len("shard-"):-len(".jsonl")].isdigit()]
        return max(idx) + 1 if idx else 0

    # ------------------------------------------------------------ writing

    def record(self, rec: dict) -> None:
        """Append one terminal-request record (host facts only — the
        scheduler's ``_retire`` hands in ints/floats it already holds)."""
        line = encode_record(rec)
        if self._corrupt_at is not None and self._records == self._corrupt_at:
            # the corrupt_log_record verb: flip the CRC nibbles so the
            # body survives but the frame fails verification — readers
            # must take the skip-with-WARN branch, exactly like bit rot
            self._corrupt_at = None
            self._injected_corrupt += 1
            crc_hex, _, body = line.partition(" ")
            line = f"{int(crc_hex, 16) ^ 0xFFFFFFFF:08x} {body}"
            self._note("corrupt_log_record")
        append_line(os.path.join(self.dir, shard_name(self._shard_index)),
                    line)
        self._records += 1
        self._open_records += 1
        self._open_bytes += len(line) + 1
        if self.rotate_bytes and self._open_bytes >= self.rotate_bytes:
            self._rotate()

    def _rotate(self) -> None:
        """Commit the open shard to the manifest and start the next one.
        The shard bytes are already durable (every record appended as it
        arrived) — the manifest replace IS the commit point, so the
        injected crash lands between the two and adoption must recover."""
        self._shards.append({"name": shard_name(self._shard_index),
                             "records": self._open_records})
        rotation = self._rotations
        self._rotations += 1
        self._shard_index += 1
        self._open_records = 0
        self._open_bytes = 0
        if (self._crash_rotate_at is not None
                and rotation == self._crash_rotate_at):
            self._crash_rotate_at = None
            self._note("crash_in_log_rotate")
            raise InjectedCrash(
                f"injected crash mid-rotation of serve-log shard "
                f"{self._shards[-1]['name']} (the shard is durable; the "
                "manifest commit never ran — adoption must recover it)")
        self._commit_manifest()
        if self.events is not None:
            # emit only AFTER the manifest commit — a crashed rotation
            # must not appear on the timeline as a committed one
            self.events.emit("logsink_rotate",
                             shard=self._shards[-1]["name"],
                             records=self._shards[-1]["records"])

    def _commit_manifest(self) -> None:
        atomic_replace(manifest_path(self.dir), json.dumps({
            "version": MANIFEST_VERSION,
            "shards": self._shards,
            "records": int(sum(s["records"] for s in self._shards)),
        }, indent=1, sort_keys=True))

    def flush(self) -> None:
        """Commit the open shard (if it holds records) so a mounting
        :class:`ServeLogSource` sees everything recorded so far."""
        if self._open_records:
            self._rotate()

    def close(self) -> None:
        self.flush()

    # -------------------------------------------------------------- chaos

    def arm_corrupt(self, nth: int, note=None) -> None:
        """``corrupt_log_record@N``: damage the CRC of the N-th record
        written (0-based, sink lifetime)."""
        self._corrupt_at = int(nth)
        self._fault_note = note

    def arm_crash_rotate(self, nth: int, note=None) -> None:
        """``crash_in_log_rotate@N``: raise after the N-th rotation's
        shard is durable but before its manifest commit (0-based)."""
        self._crash_rotate_at = int(nth)
        self._fault_note = note

    def _note(self, what: str) -> None:
        if self._fault_note is not None:
            self._fault_note(what)

    # -------------------------------------------------------------- stats

    def stats(self) -> dict:
        """Host counters for the launcher JSON line (zero device work)."""
        return {
            "records": self._records,
            "shards_committed": len(self._shards),
            "open_records": self._open_records,
            "rotations": self._rotations,
            "adopted_shards": self._adopted,
            "injected_corrupt": self._injected_corrupt,
        }


__all__ = ["LogSink"]
