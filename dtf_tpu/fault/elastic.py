"""Elastic shrink-resume: survivor-mesh arithmetic + resharding restore.

The restore half is deliberately thin: PR 3's ``zero1_param_shard_specs``
made every optimizer moment's placement a *function of the mesh*, and Orbax
restores into whatever shardings the abstract target carries — so resuming
on a smaller mesh is a **resharding restore, not a format change**. Build
the target state on the survivor mesh, restore, continue; bitwise loss
parity with an uninterrupted same-mesh run is proven on integer data in
tests/test_elastic.py.

The arithmetic half is what the controller needs BEFORE paying a relaunch:
which survivor host counts are valid (data-axis divisibility), and what the
shrunk mesh shape is. ``python -m dtf_tpu.analysis fit --hosts=N --lost=K``
prices the same shrink against an HBM budget (PR 9 planner) so the shrink
decision is made on numbers, not hope.

jax-free at module level (srclint-fenced); the restore helper imports the
backend lazily.
"""

from __future__ import annotations

from typing import Any, Callable, Mapping, Optional, Sequence

PyTree = Any


def survivor_host_count(n_hosts: int, lost: int, *, min_hosts: int = 1,
                        valid: Optional[Callable[[int], bool]] = None
                        ) -> int:
    """Hosts remaining after losing ``lost`` of ``n_hosts`` (validated)."""
    if not (0 < n_hosts):
        raise ValueError(f"n_hosts must be >= 1, got {n_hosts}")
    if not (0 <= lost < n_hosts):
        raise ValueError(
            f"lost must be in [0, {n_hosts}), got {lost}")
    n = n_hosts - lost
    if n < min_hosts:
        raise ValueError(
            f"{n} survivors < min_hosts={min_hosts}")
    if valid is not None and not valid(n):
        raise ValueError(
            f"{n} survivor hosts is not a valid mesh size")
    return n


def survivor_mesh_shape(mesh_shape: Mapping[str, int], n_hosts: int,
                        lost: int) -> dict:
    """The shrunk mesh shape: the ``data`` axis scaled to the survivors.

    Only data parallelism shrinks — model/seq/pipe/expert axes encode the
    program's structure and must survive intact (a lost host removes data
    replicas, not attention heads). Raises when the data axis cannot be
    split across the original hosts or the survivor share is fractional —
    the same precondition :func:`dtf_tpu.core.mesh.assert_host_aligned`
    enforces at launch.
    """
    survivors = survivor_host_count(n_hosts, lost)
    shape = dict(mesh_shape)
    data = shape.get("data", 1)
    if data % n_hosts:
        raise ValueError(
            f"data axis {data} not divisible across {n_hosts} hosts")
    shape["data"] = data // n_hosts * survivors
    return shape


def valid_host_counts(data_axis: int, n_hosts: int, *,
                      global_batch: Optional[int] = None) -> list[int]:
    """Survivor counts the shrink can relaunch on — the controller's
    ``valid_hosts`` predicate, precomputed.

    With the data axis split evenly across ``n_hosts`` (validated), every
    count 1..n_hosts yields a whole-shard survivor mesh by construction —
    the mesh alone rules nothing out. ``global_batch`` adds the workload
    constraint the mesh can't see: keeping the SAME global batch through
    the shrink requires it to divide the survivor data axis, or the
    relaunch dies in ``shard_batch`` instead of training.
    """
    if data_axis % n_hosts:
        raise ValueError(
            f"data axis {data_axis} not divisible across {n_hosts} hosts")
    per = data_axis // n_hosts
    return [n for n in range(1, n_hosts + 1)
            if global_batch is None or global_batch % (per * n) == 0]


def resume_state(checkpointer, init_fn, tx, rng, mesh,
                 param_rules: Sequence = (), *, zero1: bool = True,
                 step: Optional[int] = None) -> tuple[PyTree, PyTree, int]:
    """Restore the latest checkpoint ONTO ``mesh`` — resharding restore.

    Builds the abstract TrainState + shardings on the (possibly smaller)
    target mesh via ``core.train.abstract_train_state`` and hands
    Orbax the sharded abstract target: every leaf lands already laid out
    for the survivor mesh, ZeRO-1 moments re-partitioned included.
    Returns ``(state, shardings, resumed_step)``.

    The launcher path needs none of this explicitly — ``Trainer.fit``'s
    restore-if-exists does the same resharding the moment its fresh state
    was built on the smaller mesh — but the controller-driven relaunch and
    the serve tier want the restore without a Trainer.
    """
    import jax

    from dtf_tpu.core import train as tr

    abstract, shardings = tr.abstract_train_state(
        init_fn, tx, rng, mesh, param_rules, zero1=zero1)
    target = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        abstract, shardings)
    state = checkpointer.restore(target, step)
    return state, shardings, int(state.step)
