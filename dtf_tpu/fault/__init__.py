"""Elastic fault tolerance: run controller, fault injection, shrink-resume.

The chief-side half of pod-scale robustness (ROADMAP 2, docs/RESILIENCE.md):

- :mod:`~dtf_tpu.fault.controller` — the run controller state machine that
  supervises host processes, distinguishes *host-lost* (relaunch smaller,
  bounded exponential backoff) from *run-wedged* (stall watchdog fired with
  every host alive → dump postmortems, kill, relaunch same size), and stamps
  MTTR/restart counts into TELEMETRY.json.
- :mod:`~dtf_tpu.fault.inject` — the fault-injection harness: kill a host at
  a seeded step, deliver SIGTERM mid-checkpoint, wedge a step, corrupt the
  newest checkpoint. Drives the REAL launchers via ``DTF_FAULT_INJECT``.
- :mod:`~dtf_tpu.fault.elastic` — survivor-mesh arithmetic and the
  resharding resume helper (ZeRO-1 shards re-partitioned by Orbax onto the
  smaller mesh — a layout change, not a format change; docs/ZERO.md).

Like ``telemetry/`` and ``tune/``, this package is **jax-free at module
level** (srclint-fenced): the controller runs in a clean chief process that
must never be able to hang on a wedged backend import; anything needing a
backend imports it lazily inside the function that needs it.
"""

from dtf_tpu.fault.controller import (ControllerConfig, ControllerPolicy,
                                      Decision, HostObservation,
                                      RunController, read_heartbeat)
from dtf_tpu.fault.elastic import (resume_state, survivor_host_count,
                                   survivor_mesh_shape)
from dtf_tpu.fault.inject import (FaultHook, FaultPlan, StreamFaultPlan,
                                  corrupt_latest_checkpoint,
                                  corrupt_publish_version, maybe_hook,
                                  maybe_stream_fault)

__all__ = [
    "ControllerConfig", "ControllerPolicy", "Decision", "HostObservation",
    "RunController", "read_heartbeat", "FaultHook", "FaultPlan",
    "StreamFaultPlan", "corrupt_latest_checkpoint",
    "corrupt_publish_version", "maybe_hook", "maybe_stream_fault",
    "resume_state", "survivor_host_count", "survivor_mesh_shape",
]
