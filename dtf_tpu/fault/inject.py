"""Fault injection — seeded, reproducible failures against the REAL stack.

The MultiProcessRunner lineage (SURVEY.md §4) taught one lesson: recovery
paths that are not exercised do not work. This module injects the four
failure classes the run controller must survive, each at a *seeded step* so
every scenario is deterministic and its recovery assertable:

- ``kill@S``            — SIGKILL this host at step S (host-lost: no dump,
                          no save — the relaunch resumes from the last
                          periodic checkpoint on a smaller mesh).
- ``wedge@S``           — stop completing steps at S while staying alive
                          (run-wedged: the stall watchdog flags the
                          heartbeat; the controller kills and relaunches
                          at the same size).
- ``sigterm@S``         — deliver SIGTERM at the step-S boundary (graceful
                          preemption: dump → save → clean exit).
- ``sigterm_in_save@S`` — deliver SIGTERM from INSIDE ``Checkpointer.save``
                          at step S (the hard case: the flight recorder's
                          dump handler runs between the save's bytecodes —
                          the RLock/dump-first contracts from PR 5/8, end
                          to end).
- ``crash@S``           — raise at step S (in-process twin of ``kill`` for
                          tier-1 tests that cannot SIGKILL the test
                          runner; exercises the crash-postmortem path).

Plans ride the environment (``DTF_FAULT_INJECT="kill@12:host=1"``) so the
subprocess scenarios drive the real CLI entrypoints unmodified; ``host=``
scopes the fault to one fake host of the cluster sim.
:func:`corrupt_latest_checkpoint` is the offline fifth scenario: damage the
newest checkpoint so the relaunch must fall back a step (WARN, not crash —
``Checkpointer.restore``'s guarded path).

jax-free at module level (srclint-fenced) — injection is pure host/OS work.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import time
from typing import Mapping, Optional

ENV_VAR = "DTF_FAULT_INJECT"

KINDS = ("kill", "wedge", "sigterm", "sigterm_in_save", "crash",
         "crash_in_publish")

#: the SERVE-tier verbs (ISSUE 12/14) — same env var, same grammar, but
#: they target the serving pump instead of the training loop, so the
#: trainer hook (`FaultPlan.from_env`) and the serve installer
#: (:func:`ServeFaultPlan.from_env` +
#: :func:`dtf_tpu.serve.health.install_serve_fault`) each ignore the
#: other family's kinds instead of erroring on them. The hot-swap verbs:
#: ``corrupt_publish@N`` damages the N-th NEW published version the
#: swap watcher observes (0-based) before it loads — the digest check
#: must skip it with a WARN and keep the fleet on its current version;
#: ``wedge_in_swap@N:replica=k`` makes replica k's N-th ``swap_params``
#: call (0-based) sleep then raise mid-swap — the Router must roll the
#: partial fleet back onto ONE version. The serve-log verbs (ISSUE 19):
#: ``corrupt_log_record@N`` damages the CRC of the sink's N-th record
#: written (0-based) — a mounting stream source must skip it with one
#: WARN, exactly the bit-rot branch; ``crash_in_log_rotate@N`` raises
#: after the N-th rotation's shard is durable but BEFORE its manifest
#: commit — every committed record must survive via shard adoption.
#: The event-plane verb (ISSUE 20): ``crash_in_event_rotate@N`` is the
#: same crash seam on the fleet EventLog (dtf_tpu/telemetry/events.py) —
#: the next mount must ADOPT the orphaned event shard and the timeline
#: must still close every episode.
SERVE_KINDS = ("wedge_replica", "slow_decode", "poison_request",
               "poison_draft", "corrupt_publish", "wedge_in_swap",
               "corrupt_log_record", "crash_in_log_rotate",
               "crash_in_event_rotate")

#: the STREAMING-DATA-TIER verbs (ISSUE 15) — same env var, same grammar,
#: targeting the mixture stream's producer (dtf_tpu/data/stream) instead
#: of the training loop or the serving pump; every installer family
#: ignores the others' kinds. ``stall_source@S[:source=k]`` makes source
#: k's draws at step S block for the stream's stall window (a slow/hung
#: reader: the bounded producer queue drains, ``data_wait`` spikes, the
#: run must CONTINUE and the realized batches must be byte-identical —
#: stalls are latency-only). ``corrupt_record@S[:source=k]`` poisons the
#: next record source k reads after step S so the CRC check fails — the
#: stream must skip it with a WARN, exactly the on-disk bit-rot path.
STREAM_KINDS = ("stall_source", "corrupt_record")


class InjectedCrash(RuntimeError):
    """The ``crash@S`` payload — a host died, in exception form."""


class InjectedPoison(RuntimeError):
    """The ``poison_request@N`` payload — a request whose prefill raises
    wherever it lands (serve chaos: the scheduler must isolate it)."""


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """One seeded fault: ``<kind>@<step>[:host=<k>]``."""

    kind: str
    step: int
    host: Optional[int] = None     # None = every host

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; have {KINDS}")
        if self.step < 0:
            raise ValueError(f"fault step must be >= 0, got {self.step}")

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        body, _, tail = spec.strip().partition(":")
        kind, at, step = body.partition("@")
        if not at:
            raise ValueError(
                f"fault spec {spec!r} needs '<kind>@<step>'")
        host = None
        if tail:
            key, _, val = tail.partition("=")
            if key != "host":
                raise ValueError(
                    f"unknown fault option {key!r} in {spec!r}")
            host = int(val)
        return cls(kind=kind, step=int(step), host=host)

    @classmethod
    def from_env(cls, env: Optional[Mapping] = None) -> Optional["FaultPlan"]:
        spec = (env if env is not None else os.environ).get(ENV_VAR, "")
        kind = spec.partition("@")[0].strip()
        if not spec or kind in SERVE_KINDS or kind in STREAM_KINDS:
            return None   # serve/stream verbs ride past the trainer hook
        return cls.parse(spec)

    def applies_to(self, host_index: int) -> bool:
        return self.host is None or self.host == host_index


@dataclasses.dataclass(frozen=True)
class ServeFaultPlan:
    """One seeded SERVE fault: ``<kind>@<tick>[:replica=<k>]``.

    ``tick`` is counted in the target's own call domain — the k-th decode
    call of the wedged/slowed replica's engine, or the N-th submit for
    ``poison_request`` — so a plan is deterministic under open-loop
    Poisson timing. ``replica=None`` targets every replica (poison plans
    ignore the option: the poisoned request raises wherever it lands).
    """

    kind: str
    tick: int
    replica: Optional[int] = None

    def __post_init__(self):
        if self.kind not in SERVE_KINDS:
            raise ValueError(
                f"unknown serve fault kind {self.kind!r}; have {SERVE_KINDS}")
        if self.tick < 0:
            raise ValueError(f"fault tick must be >= 0, got {self.tick}")

    @classmethod
    def parse(cls, spec: str) -> "ServeFaultPlan":
        body, _, tail = spec.strip().partition(":")
        kind, at, tick = body.partition("@")
        if not at:
            raise ValueError(f"fault spec {spec!r} needs '<kind>@<tick>'")
        replica = None
        if tail:
            key, _, val = tail.partition("=")
            if key != "replica":
                raise ValueError(
                    f"unknown serve fault option {key!r} in {spec!r}")
            replica = int(val)
        return cls(kind=kind.strip(), tick=int(tick), replica=replica)

    @classmethod
    def from_env(cls, env: Optional[Mapping] = None
                 ) -> Optional["ServeFaultPlan"]:
        spec = (env if env is not None else os.environ).get(ENV_VAR, "")
        if not spec or spec.partition("@")[0].strip() not in SERVE_KINDS:
            return None        # trainer verbs ride past the serve installer
        return cls.parse(spec)


@dataclasses.dataclass(frozen=True)
class StreamFaultPlan:
    """One seeded STREAM fault: ``<kind>@<step>[:source=<k>]``.

    ``step`` is the mixture stream's global step (the batch index the
    producer is building); ``source=None`` targets source 0 — a stream
    fault needs a concrete victim, and 0 is the deterministic default.
    Armed by :meth:`dtf_tpu.data.stream.MixtureStream.arm_fault` (the
    launchers install it via ``maybe_stream_fault``); the trainer hook and
    the serve installer each ignore this family's kinds.
    """

    kind: str
    step: int
    source: Optional[int] = None

    def __post_init__(self):
        if self.kind not in STREAM_KINDS:
            raise ValueError(
                f"unknown stream fault kind {self.kind!r}; "
                f"have {STREAM_KINDS}")
        if self.step < 0:
            raise ValueError(f"fault step must be >= 0, got {self.step}")

    @classmethod
    def parse(cls, spec: str) -> "StreamFaultPlan":
        body, _, tail = spec.strip().partition(":")
        kind, at, step = body.partition("@")
        if not at:
            raise ValueError(f"fault spec {spec!r} needs '<kind>@<step>'")
        source = None
        if tail:
            key, _, val = tail.partition("=")
            if key != "source":
                raise ValueError(
                    f"unknown stream fault option {key!r} in {spec!r}")
            source = int(val)
        return cls(kind=kind.strip(), step=int(step), source=source)

    @classmethod
    def from_env(cls, env: Optional[Mapping] = None
                 ) -> Optional["StreamFaultPlan"]:
        spec = (env if env is not None else os.environ).get(ENV_VAR, "")
        if not spec or spec.partition("@")[0].strip() not in STREAM_KINDS:
            return None   # trainer/serve verbs ride past the stream arm
        return cls.parse(spec)


def maybe_stream_fault(env: Optional[Mapping] = None
                       ) -> Optional[StreamFaultPlan]:
    """The stream builders' one-liner: a StreamFaultPlan when
    ``DTF_FAULT_INJECT`` names a stream verb, else None."""
    return StreamFaultPlan.from_env(env)


class FaultHook:
    """Trainer hook that executes a :class:`FaultPlan` at its seeded step.

    Duck-typed against :class:`dtf_tpu.hooks.Hook` (no jax import). Place
    it FIRST in the hook list: the injected SIGTERM must land before
    PreemptionHook's ``after_step`` runs at the same boundary, so the hook
    saves the exact seeded step. ``checkpointer`` is required for
    ``sigterm_in_save`` (its ``save`` is wrapped so the signal arrives
    mid-write). Each firing prints one JSON line first — a scenario whose
    recovery assertion fails must still show WHERE the fault landed.
    """

    telemetry_bucket = "hooks"

    #: wedge sleep quantum — short enough that SIGKILL tests reap quickly
    WEDGE_POLL_S = 0.5

    def __init__(self, plan: FaultPlan, *, host_index: int = 0,
                 checkpointer=None, publisher=None, emit=None):
        self.plan = plan
        self.host_index = host_index
        self.ckpt = checkpointer
        self._emit = emit or (lambda line: print(line, flush=True))
        self.fired = False
        if (plan.kind == "sigterm_in_save" and checkpointer is not None
                and plan.applies_to(host_index)):
            self._wrap_save(checkpointer)
        if (plan.kind == "crash_in_publish" and publisher is not None
                and plan.applies_to(host_index)):
            self._wrap_publish(publisher)

    def _note(self, what: str) -> None:
        try:
            self._emit(json.dumps({
                "fault_inject": what, "kind": self.plan.kind,
                "step": self.plan.step, "host": self.host_index,
                # clock-ok: real wall stamp correlated with controller logs
                "pid": os.getpid(), "t": round(time.time(), 3)}))
        except Exception:   # noqa: BLE001 — injection reporting must not
            pass            # alter the scenario under test

    def _wrap_save(self, ckpt) -> None:
        orig = ckpt.save
        plan = self.plan

        def save(step, state, **kw):
            if not self.fired and step >= plan.step:
                self.fired = True
                self._note("sigterm_in_save")
                # handled at the next bytecode boundary: the telemetry
                # dump + PreemptionHook flag run INSIDE this save call
                os.kill(os.getpid(), signal.SIGTERM)
            return orig(step, state, **kw)

        ckpt.save = save

    def _wrap_publish(self, publisher) -> None:
        """Arm the ``crash_in_publish`` window: the publisher's
        ``_pre_commit`` seam sits AFTER the version data is durable and
        BEFORE the manifest rename — the crash must leave the previous
        manifest (and version) fully servable (dtf_tpu/publish.py's
        atomicity contract, proven by the swap chaos tests)."""
        plan = self.plan

        def pre_commit(version, step):
            if not self.fired and step >= plan.step:
                self.fired = True
                self._note("crash_in_publish")
                raise InjectedCrash(
                    f"injected crash mid-publish of version {version} "
                    f"(step {step}, host {self.host_index})")

        publisher._pre_commit = pre_commit

    # ------------------------------------------------------- hook lifecycle

    def begin(self, state) -> None: ...

    def before_step(self, step: int) -> None: ...

    def after_step(self, step: int, state, metrics) -> None:
        plan = self.plan
        if (self.fired
                or plan.kind in ("sigterm_in_save", "crash_in_publish")
                or not plan.applies_to(self.host_index)
                or step < plan.step):
            return
        self.fired = True
        self._note("firing")
        if plan.kind == "kill":
            os.kill(os.getpid(), signal.SIGKILL)
        elif plan.kind == "sigterm":
            os.kill(os.getpid(), signal.SIGTERM)
        elif plan.kind == "crash":
            raise InjectedCrash(
                f"injected crash at step {step} (host {self.host_index})")
        elif plan.kind == "wedge":
            # alive but never completing another step: SIGTERM only sets
            # the PreemptionHook flag (checked at a boundary this loop
            # will never reach again), so like a real wedge it takes the
            # controller's SIGKILL to clear — sleep in short quanta so
            # the process stays signal-responsive for the dump chain.
            while True:
                # the wedge must burn REAL wall time — it is the thing
                # the watchdog's stall detection measures
                # clock-ok: a real wedge sleeps on the real clock
                time.sleep(self.WEDGE_POLL_S)

    def end(self, state) -> None: ...


def maybe_hook(*, host_index: int = 0, checkpointer=None, publisher=None,
               env: Optional[Mapping] = None) -> Optional[FaultHook]:
    """The launchers' one-liner: a FaultHook when ``DTF_FAULT_INJECT`` is
    set and targets this host, else None."""
    plan = FaultPlan.from_env(env)
    if plan is None or not plan.applies_to(host_index):
        return None
    return FaultHook(plan, host_index=host_index, checkpointer=checkpointer,
                     publisher=publisher)


# ---------------------------------------------------------------------------
# Checkpoint corruption (the restore-fallback scenario).
# ---------------------------------------------------------------------------

def _corrupt_tree(root: str, mode: str, min_bytes: int) -> list[str]:
    touched = []
    for walk_root, _, files in os.walk(root):
        for name in files:
            path = os.path.join(walk_root, name)
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            if size < min_bytes:
                continue
            if mode == "truncate":
                # io-ok: deliberately non-atomic — this IS the damage
                with open(path, "r+b") as f:
                    f.truncate(size // 2)
            else:
                # io-ok: deliberately non-atomic — this IS the damage
                with open(path, "r+b") as f:
                    f.write(b"\xde\xad\xbe\xef" * 4)
            touched.append(os.path.relpath(path, root))
    return touched


def corrupt_publish_version(publish_dir: str, version: int, *,
                            mode: str = "garbage",
                            min_bytes: int = 1) -> dict:
    """Damage one PUBLISHED version's files (the ``corrupt_publish``
    serve verb, ISSUE 14): the watcher's digest check must then skip the
    version with a WARN and the fleet keeps serving what it has. Same
    damage modes as :func:`corrupt_latest_checkpoint`."""
    if mode not in ("truncate", "garbage"):
        raise ValueError(f"unknown corruption mode {mode!r}")
    root = os.path.join(publish_dir, str(int(version)))
    if not os.path.isdir(root):
        raise FileNotFoundError(
            f"no published version {version} under {publish_dir}")
    return {"version": int(version),
            "files": sorted(_corrupt_tree(root, mode, min_bytes))}


def corrupt_latest_checkpoint(ckpt_dir: str, *, mode: str = "truncate",
                              min_bytes: int = 1) -> dict:
    """Damage the newest checkpoint step so restore must fall back.

    ``truncate`` halves every data file in the step dir (a host died
    mid-write after the atomic rename — rare but real on network
    filesystems); ``garbage`` overwrites their heads. Orbax's own
    atomicity makes a *cleanly interrupted* save invisible, so this
    simulates the uglier post-commit damage class. Returns
    ``{"step": n, "files": [...]}``; raises FileNotFoundError when no
    step dir exists (a scenario that corrupts nothing is not testing the
    fallback).
    """
    if mode not in ("truncate", "garbage"):
        raise ValueError(f"unknown corruption mode {mode!r}")
    steps = [int(d) for d in os.listdir(ckpt_dir) if d.isdigit()]
    if not steps:
        raise FileNotFoundError(f"no checkpoint steps under {ckpt_dir}")
    step = max(steps)
    step_dir = os.path.join(ckpt_dir, str(step))
    touched = [os.path.join(str(step), rel)
               for rel in _corrupt_tree(step_dir, mode, min_bytes)]
    return {"step": step, "files": sorted(touched)}
