"""``python -m dtf_tpu.fault`` — run a command fleet under the controller.

    python -m dtf_tpu.fault --hosts=2 --logdir=/tmp/run \\
        --max-restarts=3 --valid-hosts=1,2 -- \\
        python scripts/distributed.py --backend=cpu --logdir=/tmp/run \\
            --worker_hosts={worker_hosts} --task_index={host} \\
            --devices_per_host=4 --telemetry

The command after ``--`` is a template launched once per host with
``{host}`` (this host's index), ``{hosts}`` (current host count) and
``{worker_hosts}`` (a synthesized ``h0,h1,...`` list of the right length)
substituted — on relaunch after a host loss the count shrinks, so the
workers re-form a smaller mesh and resume by resharding (docs/RESILIENCE.md).

Output: controller transition JSON lines, then the summary as the LAST line
(the bench.py contract). Exit 0 on ``final: done``, 1 otherwise. jax-free —
this process must never be able to hang on a wedged backend.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from dtf_tpu.fault.controller import ControllerConfig, RunController
from dtf_tpu.fault.inject import ENV_VAR as _FAULT_ENV


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--" not in argv:
        print(json.dumps({"ok": False,
                          "error": "usage: python -m dtf_tpu.fault "
                                   "[options] -- <command template>"}))
        return 2
    split = argv.index("--")
    template = argv[split + 1:]
    parser = argparse.ArgumentParser(prog="python -m dtf_tpu.fault")
    parser.add_argument("--hosts", type=int, required=True)
    parser.add_argument("--logdir", required=True)
    parser.add_argument("--max-restarts", type=int, default=3)
    parser.add_argument("--backoff-base-s", type=float, default=1.0)
    parser.add_argument("--backoff-max-s", type=float, default=60.0)
    parser.add_argument("--wedge-timeout-s", type=float, default=120.0)
    parser.add_argument("--startup-timeout-s", type=float, default=600.0)
    parser.add_argument("--grace-s", type=float, default=15.0)
    parser.add_argument("--valid-hosts", default="",
                        help="comma-separated allowed host counts "
                             "(default: any >= 1); mesh divisibility — "
                             "pre-price with `analysis fit --hosts --lost`")
    parser.add_argument("--telemetry-artifact", default="",
                        help="merge the MTTR/restart summary into this "
                             "TELEMETRY.json")
    args = parser.parse_args(argv[:split])
    if not template:
        print(json.dumps({"ok": False, "error": "empty command template"}))
        return 2

    valid = None
    if args.valid_hosts:
        allowed = {int(x) for x in args.valid_hosts.split(",") if x}
        valid = allowed.__contains__

    def launch(n_hosts: int, attempt: int) -> list:
        worker_hosts = ",".join(f"host{i}" for i in range(n_hosts))
        env = dict(os.environ)
        if attempt > 0:
            # an injected fault is a one-shot scenario: FaultHook fires at
            # step >= plan.step, and a relaunch resumes from a checkpoint
            # that can be PAST it — re-tripping the same fault every
            # generation would turn a recoverable kill/wedge into a
            # max-restarts exhaustion
            env.pop(_FAULT_ENV, None)
        procs = []
        for host in range(n_hosts):
            cmd = [t.format(host=host, hosts=n_hosts,
                            worker_hosts=worker_hosts) for t in template]
            procs.append(subprocess.Popen(cmd, env=env))
        return procs

    ctl = RunController(
        launch, args.hosts, args.logdir,
        ControllerConfig(
            max_restarts=args.max_restarts,
            backoff_base_s=args.backoff_base_s,
            backoff_max_s=args.backoff_max_s,
            wedge_timeout_s=args.wedge_timeout_s,
            startup_timeout_s=args.startup_timeout_s,
            grace_s=args.grace_s),
        valid_hosts=valid)
    summary = ctl.run()
    ctl.finish(summary, args.telemetry_artifact or None)
    print(json.dumps(summary))
    return 0 if summary.get("final") == "done" else 1


if __name__ == "__main__":
    sys.exit(main())
