"""Chief-side elastic run controller — the relaunch policy in one place.

At pod scale, preemption and host failure are the steady state (MLPerf on
TPU-v3 pods, pjit on TPUv4 — PAPERS.md). The pieces below the controller
already exist: every host runs a flight recorder whose stall watchdog writes
a liveness heartbeat (PR 5/this PR, ``telemetry/flight.py``), PreemptionHook
turns SIGTERM into a durable save + clean exit, and Orbax restore reshards
onto whatever mesh the relaunch brings up (``fault/elastic.py``). What was
missing is the process that *owns the decision*: watch N host processes,
tell **host-lost** from **run-wedged**, and relaunch accordingly.

The two verdicts and their policies (docs/RESILIENCE.md):

- **host-lost** — a host process died (SIGKILL'd by the cluster manager,
  OOM, hardware). Survivors cannot make progress (collectives block), so:
  stop the survivors (SIGTERM first — their dump chain writes postmortems
  and a final checkpoint), then relaunch on the largest valid smaller host
  count, under bounded exponential backoff and a max-restarts budget.
- **run-wedged** — every host process is alive but no step completes: a
  host's stall watchdog flagged its heartbeat ``stalled``, or heartbeats
  went stale, or a launch never produced one. Nothing is gone, something
  is stuck (dead tunnel, deadlocked collective): dump postmortems
  everywhere (the SIGTERM chain does — flight dump first, then the
  checkpoint), kill, relaunch at the SAME size.

Every transition is emitted as one JSON line (the bench.py idiom) and
appended to ``<logdir>/controller.jsonl``; ``finish()`` stamps the run's
restart count and per-restart MTTR into TELEMETRY.json.

Module-level jax-free (srclint-fenced): the controller must run in a clean
process that cannot hang on a wedged backend — it observes hosts through
the filesystem and the process table only.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Callable, Mapping, Optional, Sequence

from dtf_tpu._hostio import append_line


def read_heartbeat(path: str) -> Optional[dict]:
    """The host's last liveness record, or None. Never raises — a torn
    write (the host died mid-rename) reads as 'no heartbeat', which the
    staleness rules already handle."""
    try:
        with open(path) as f:
            data = json.load(f)
        return data if isinstance(data, dict) else None
    except (OSError, ValueError):
        return None


@dataclasses.dataclass(frozen=True)
class ControllerConfig:
    """The retry/timeout/backoff policy knobs."""

    max_restarts: int = 3
    backoff_base_s: float = 1.0       # exponential: base * 2**restart
    backoff_max_s: float = 60.0
    #: heartbeat older than this on a live process = wedged
    wedge_timeout_s: float = 120.0
    #: a launch that never produced a heartbeat within this = wedged
    startup_timeout_s: float = 600.0
    #: SIGTERM → SIGKILL grace when stopping hosts (the dump/save window)
    grace_s: float = 15.0
    poll_s: float = 0.5
    min_hosts: int = 1


@dataclasses.dataclass(frozen=True)
class HostObservation:
    """One host's state at one poll — everything classify() looks at."""

    host: int
    alive: bool
    returncode: Optional[int]
    #: seconds since the heartbeat's own wall stamp; None = no heartbeat
    heartbeat_age_s: Optional[float]
    last_step: Optional[int] = None
    #: the host's own stall watchdog fired (heartbeat ``stalled`` flag)
    stalled: bool = False


@dataclasses.dataclass(frozen=True)
class Decision:
    """One policy verdict: what happened and what to do about it."""

    kind: str                 # running | done | host_lost | wedged
    reason: str = ""
    dead_hosts: tuple = ()
    wedged_hosts: tuple = ()


class ControllerPolicy:
    """The pure state machine — classify observations, size the relaunch.

    Separated from :class:`RunController` so every branch is unit-testable
    with hand-built observations (tier-1 fast), while the controller owns
    only process plumbing.
    """

    def classify(self, obs: Sequence[HostObservation], *,
                 config: ControllerConfig,
                 since_launch_s: float) -> Decision:
        dead = tuple(o.host for o in obs
                     if not o.alive and o.returncode != 0)
        if dead:
            return Decision(
                "host_lost", dead_hosts=dead,
                reason=f"host(s) {list(dead)} exited "
                       f"{[o.returncode for o in obs if o.host in dead]}")
        if all(not o.alive for o in obs):        # every rc == 0
            return Decision("done", reason="all hosts exited 0")
        # some/all alive, none failed: wedge checks apply to live hosts
        wedged = []
        for o in obs:
            if not o.alive:
                continue
            if o.stalled:
                wedged.append((o.host, "stall watchdog fired"))
            elif (o.heartbeat_age_s is not None
                  and o.heartbeat_age_s > config.wedge_timeout_s):
                wedged.append(
                    (o.host,
                     f"heartbeat stale {o.heartbeat_age_s:.0f}s"))
            elif (o.heartbeat_age_s is None
                  and since_launch_s > config.startup_timeout_s):
                wedged.append(
                    (o.host,
                     f"no heartbeat {since_launch_s:.0f}s after launch"))
        if wedged:
            return Decision(
                "wedged", wedged_hosts=tuple(h for h, _ in wedged),
                reason="; ".join(f"host {h}: {why}" for h, why in wedged))
        return Decision("running")

    def shrink(self, n_hosts: int, n_dead: int, *,
               config: ControllerConfig,
               valid: Optional[Callable[[int], bool]] = None
               ) -> Optional[int]:
        """Largest valid survivor count, or None (no valid shrink left).

        ``valid`` encodes mesh divisibility (the ``analysis fit
        --hosts/--lost`` pre-pricing feeds the same predicate): the data
        axis must split evenly across the survivors or the relaunch would
        die in ``make_mesh`` instead of training.
        """
        valid = valid or (lambda n: True)
        for n in range(n_hosts - max(n_dead, 1), config.min_hosts - 1, -1):
            if n >= config.min_hosts and valid(n):
                return n
        return None

    def backoff_s(self, restarts: int, config: ControllerConfig) -> float:
        return min(config.backoff_base_s * (2 ** restarts),
                   config.backoff_max_s)


class RunController:
    """Supervise N host processes through failures to completion.

    ``launch(n_hosts, attempt) -> list[proc]`` starts one OS process per
    host and returns handles exposing ``poll() -> rc|None``, ``pid``,
    ``terminate()``, ``kill()`` (``subprocess.Popen`` as-is; tests pass
    fakes). ``heartbeat_path(host) -> path`` locates each host's liveness
    file (default: ``<logdir>/telemetry/p<host>/heartbeat.json``, the
    multi-process telemetry layout; single-host runs fall back to the
    unsuffixed dir). ``valid_hosts(n) -> bool`` gates shrink sizes on mesh
    divisibility. ``clock``/``wall``/``sleep`` are injectable so the whole
    supervision loop unit-tests in milliseconds.
    """

    def __init__(self, launch: Callable[[int, int], list], n_hosts: int,
                 logdir: str, config: ControllerConfig = ControllerConfig(),
                 *, policy: Optional[ControllerPolicy] = None,
                 heartbeat_path: Optional[Callable[[int], str]] = None,
                 valid_hosts: Optional[Callable[[int], bool]] = None,
                 emit: Callable[[str], None] = None,
                 clock=time.monotonic, wall=time.time, sleep=time.sleep,
                 event_log=None):
        if n_hosts < 1:
            raise ValueError(f"n_hosts must be >= 1, got {n_hosts}")
        self.launch = launch
        self.n_hosts = n_hosts
        self.logdir = logdir
        self.config = config
        self.policy = policy or ControllerPolicy()
        self.heartbeat_path = heartbeat_path or self._default_hb_path
        self.valid_hosts = valid_hosts
        self._emit_fn = emit or (lambda line: print(line, flush=True))
        self.clock = clock
        self.wall = wall
        self.sleep = sleep
        self.events: list[dict] = []
        #: optional fleet EventLog (ISSUE 20): every verdict the
        #: controller emits is mirrored onto the run timeline with the
        #: controller's OWN wall stamp (MTTR ground truth).
        self.event_log = event_log
        self.mttr_s: list[float] = []
        self.restarts = 0
        self.causes: list[str] = []

    # ------------------------------------------------------------- plumbing

    def _default_hb_path(self, host: int) -> str:
        """Multi-process telemetry writes per-host ``p<i>/heartbeat.json``;
        single-process writes the unsuffixed file — after an elastic
        shrink to one host the same controller must follow along, so
        prefer whichever exists (stamp filtering discards a stale
        ``p<i>`` file left by the bigger fleet)."""
        base = os.path.join(self.logdir, "telemetry")
        suffixed = os.path.join(base, f"p{host}", "heartbeat.json")
        plain = os.path.join(base, "heartbeat.json")
        if host == 0 and os.path.exists(plain):
            if not os.path.exists(suffixed):
                return plain
            # both exist (a shrink crossed the naming boundary): the one
            # beating NOW is the one with the newer stamp
            ts = (read_heartbeat(suffixed) or {}).get("t", 0)
            tp = (read_heartbeat(plain) or {}).get("t", 0)
            return suffixed if ts >= tp else plain
        return suffixed if self.n_hosts > 1 else plain

    def _emit(self, event: Mapping) -> dict:
        rec = {"controller": "event", "t": round(self.wall(), 3), **event}
        self.events.append(rec)
        line = json.dumps(rec)
        try:
            self._emit_fn(line)
        except Exception:   # noqa: BLE001 — an emit sink must not kill
            pass            # the supervision loop
        try:
            append_line(os.path.join(self.logdir, "controller.jsonl"),
                        line)
        except OSError:
            pass
        if self.event_log is not None:
            fields = {k: v for k, v in rec.items()
                      if k not in ("controller", "t", "state", "hosts")}
            # "hosts" is the bulky per-host observation dump — it stays
            # in controller.jsonl; the timeline carries the verdict
            state = event.get("state", rec.get("controller", "event"))
            self.event_log.emit(f"controller_{state}", t=rec["t"],
                                **fields)
        return rec

    def _observe(self, procs: Sequence,
                 launched_wall: float) -> list[HostObservation]:
        """Poll liveness + heartbeats. A heartbeat stamped BEFORE this
        attempt's launch is a previous incarnation's last word (possibly
        ``stalled: true`` from the wedge that caused the relaunch) and is
        treated as absent — the startup-timeout rule governs until the new
        processes write their own."""
        now_wall = self.wall()
        obs = []
        for host, p in enumerate(procs):
            rc = p.poll()
            hb = read_heartbeat(self.heartbeat_path(host))
            age = None
            step = None
            stalled = False
            if hb is not None:
                try:
                    t = float(hb.get("t", 0.0))
                except (TypeError, ValueError):
                    t = None
                if t is not None and t >= launched_wall:
                    age = max(now_wall - t, 0.0)
                    step = hb.get("step")
                    stalled = bool(hb.get("stalled"))
            obs.append(HostObservation(
                host=host, alive=rc is None, returncode=rc,
                heartbeat_age_s=age, last_step=step, stalled=stalled))
        return obs

    def _stop_procs(self, procs: Sequence, *, reason: str) -> None:
        """SIGTERM every live host (their chain dumps a postmortem, then
        PreemptionHook checkpoints), wait the grace window, SIGKILL the
        rest. A wedged host by definition may ignore the SIGTERM — the
        grace bound is what keeps the controller from joining it."""
        live = [p for p in procs if p.poll() is None]
        for p in live:
            try:
                p.terminate()
            except (OSError, ProcessLookupError):
                pass
        deadline = self.clock() + self.config.grace_s
        while self.clock() < deadline:
            if all(p.poll() is not None for p in live):
                break
            self.sleep(min(self.config.poll_s, 0.2))
        killed = []
        for p in live:
            if p.poll() is None:
                killed.append(getattr(p, "pid", None))
                try:
                    p.kill()
                except (OSError, ProcessLookupError):
                    pass
        if killed:
            self._emit({"state": "killed", "reason": reason,
                        "pids": killed})

    @staticmethod
    def _fresh(o: HostObservation, config: ControllerConfig) -> bool:
        return (o.alive and o.heartbeat_age_s is not None
                and o.heartbeat_age_s <= config.wedge_timeout_s
                and not o.stalled)

    # ------------------------------------------------------------ main loop

    def run(self) -> dict:
        """Supervise to completion; returns the summary dict (also the
        last emitted event). Raises nothing on policy failures — a
        ``final: failed`` summary with the cause IS the loud failure."""
        cfg = self.config
        n = self.n_hosts
        pending_mttr: Optional[float] = None
        while True:
            self._emit({"state": "launching", "n_hosts": n,
                        "restarts": self.restarts})
            # wall stamp BEFORE launch: a heartbeat written during the
            # launch callback (or by a worker that starts instantly) must
            # count as THIS attempt's, while anything older is a previous
            # incarnation's last word
            launched = self.clock()
            launched_wall = self.wall()
            procs = list(self.launch(n, self.restarts))
            recovered_logged = pending_mttr is None
            while True:
                obs = self._observe(procs, launched_wall)
                if not recovered_logged and any(
                        self._fresh(o, cfg) for o in obs):
                    mttr = self.wall() - pending_mttr
                    self.mttr_s.append(round(mttr, 3))
                    pending_mttr = None
                    recovered_logged = True
                    self._emit({"state": "recovered",
                                "mttr_s": round(mttr, 3), "n_hosts": n})
                d = self.policy.classify(
                    obs, config=cfg,
                    since_launch_s=self.clock() - launched)
                if d.kind == "running":
                    self.sleep(cfg.poll_s)
                    continue
                if d.kind == "done":
                    self._emit({"state": "done", "reason": d.reason,
                                "n_hosts": n})
                    return self._summary("done", n)
                # ---- failure detected --------------------------------
                t_detect = self.wall()
                self.causes.append(d.kind)
                self._emit({
                    "state": d.kind, "reason": d.reason, "n_hosts": n,
                    "dead_hosts": list(d.dead_hosts),
                    "wedged_hosts": list(d.wedged_hosts),
                    "hosts": [dataclasses.asdict(o) for o in obs]})
                self._stop_procs(procs, reason=d.kind)
                if self.restarts >= cfg.max_restarts:
                    self._emit({"state": "failed",
                                "reason": f"max_restarts={cfg.max_restarts}"
                                          f" exhausted after {d.kind}"})
                    return self._summary("failed", n, cause=d.kind)
                if d.kind == "host_lost":
                    n_next = self.policy.shrink(
                        n, len(d.dead_hosts), config=cfg,
                        valid=self.valid_hosts)
                    if n_next is None:
                        self._emit({"state": "failed",
                                    "reason": "no valid survivor host "
                                              f"count below {n}"})
                        return self._summary("failed", n, cause=d.kind)
                else:
                    n_next = n
                backoff = self.policy.backoff_s(self.restarts, cfg)
                self.restarts += 1
                self._emit({"state": "relaunching", "cause": d.kind,
                            "n_hosts": n_next, "backoff_s": backoff,
                            "restarts": self.restarts})
                self.sleep(backoff)
                pending_mttr = t_detect
                n = n_next
                break       # relaunch

    def _summary(self, final: str, n_hosts: int, *,
                 cause: Optional[str] = None) -> dict:
        out = {
            "controller": "summary",
            "final": final,
            "n_hosts_initial": self.n_hosts,
            "n_hosts_final": n_hosts,
            "restarts": self.restarts,
            "causes": list(self.causes),
            "mttr_s": list(self.mttr_s),
        }
        if self.mttr_s:
            out["mttr_mean_s"] = round(sum(self.mttr_s)
                                       / len(self.mttr_s), 3)
        if cause:
            out["cause"] = cause
        self._emit(out)
        return out

    def finish(self, summary: Mapping,
               telemetry_artifact: Optional[str] = None,
               meta: Optional[Mapping] = None) -> Optional[dict]:
        """Stamp the run's MTTR/restart fields into TELEMETRY.json
        (``telemetry.run.merge_artifact`` — jax-free, same bounded-runs
        layout the RunReports use). Always emits the terminal ``run_end``
        event FIRST (ISSUE 20 satellite): the timeline must close every
        episode even when the artifact merge is skipped."""
        if self.event_log is not None:
            self.event_log.emit(
                "run_end", final=summary.get("final", "unknown"),
                restarts=int(summary.get("restarts", self.restarts)),
                causes=list(summary.get("causes", self.causes)),
                mttr_s=list(summary.get("mttr_s", self.mttr_s)),
                t=round(self.wall(), 3))
            self.event_log.flush()   # commit: the timeline reads it now
        if not telemetry_artifact:
            return None
        from dtf_tpu.telemetry.run import merge_artifact

        entry = {"telemetry": "controller", **summary}
        return merge_artifact(telemetry_artifact, entry, meta=meta)
