"""Backfill newer-JAX surface onto older installs (one-way, idempotent).

The codebase targets the post-0.5 spellings — ``jax.shard_map`` with
``check_vma=``, ``jax.sharding.AxisType``, ``jax.make_mesh(axis_types=)`` —
because those are what the real-chip environment runs.  Some containers pin
an older jax (0.4.x) where the same features exist under their previous
names (``jax.experimental.shard_map.shard_map(check_rep=)``, no axis-type
enum, no ``axis_types`` kwarg).  Rather than fork every call site, this
module adapts the old API to the new spelling at import time:

- ``jax.shard_map``      → wraps the experimental one, mapping
  ``check_vma`` → ``check_rep`` (same meaning: verify replication/varying
  manual-axes typing of outputs).
- ``jax.sharding.AxisType`` → a stand-in enum; pre-0.5 meshes are always
  fully Auto, which is exactly what every call site requests.
- ``jax.make_mesh``      → accepts and drops ``axis_types`` (Auto is the
  0.4.x behavior already).

Importing this module on a new-enough jax is a no-op.  It must be imported
before any call site runs; ``dtf_tpu/__init__.py`` does so, and the test
conftest imports ``dtf_tpu`` modules before using ``jax.shard_map``
directly.
"""

from __future__ import annotations

import enum
import functools
import inspect

import jax


#: True when this module actually backfilled anything (i.e. the install is
#: pre-0.5 jax). Callers use it to gate version-specific workarounds, e.g.
#: tests/conftest.py disables the persistent compilation cache on old jax
#: (deserialized executables there can drop mutable-collection outputs).
BACKFILLED = False


def fp8_e4m3_dtype():
    """The fp8 e4m3 storage dtype, or None on a jax without fp8 support.

    The low-precision matmul tier (``ops/quant.py``) feature-gates its
    fp8 path here: where the dtype is missing, an fp8 precision request
    demotes to bf16 with one warning instead of crashing a launcher on
    an old install (docs/TUNING.md "Precision winners")."""
    import jax.numpy as jnp

    return getattr(jnp, "float8_e4m3fn", None)


def _install() -> None:
    global BACKFILLED
    BACKFILLED = not hasattr(jax, "shard_map")
    # Newer jax defaults this on; without it, random bits (param init,
    # dropout) depend on how XLA partitions the rng op, so the SAME seed
    # yields DIFFERENT initial params on different meshes — every
    # TP/SP-vs-DP parity property assumes sharding-invariant randomness.
    if not jax.config.jax_threefry_partitionable:
        jax.config.update("jax_threefry_partitionable", True)
    if not hasattr(jax.sharding, "AxisType"):
        class AxisType(enum.Enum):
            Auto = "auto"
            Explicit = "explicit"
            Manual = "manual"

        jax.sharding.AxisType = AxisType

    if not hasattr(jax.distributed, "is_initialized"):
        # newer-jax API core/dist.py guards re-initialization with; on
        # this jax the fact lives on the private global coordination
        # state (client is None until initialize() connects it)
        def _dist_is_initialized():
            from jax._src import distributed as _dist

            return _dist.global_state.client is not None

        jax.distributed.is_initialized = _dist_is_initialized

    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map

        @functools.wraps(_shard_map)
        def shard_map(f, /, *, mesh, in_specs, out_specs, check_vma=True,
                      **kwargs):
            # check_vma maps to check_rep=False unconditionally: the 0.4.x
            # replication checker predates the vma type system and raises
            # spurious "mismatched replication types" on cond/scan bodies
            # the new checker accepts.  This only drops a static check —
            # gradient semantics are covered by the suite's parity tests
            # (ring-attention vs dense, pipeline vs unpipelined, fused-CE
            # sharded vs local).
            del check_vma
            return _shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=False,
                              **kwargs)

        jax.shard_map = shard_map

    if not hasattr(jax.lax, "pcast"):
        def pcast(x, axes, *, to):
            # Explicit replicated→varying promotion only exists under the
            # vma type system; with check_rep=False it is a no-op.
            del axes, to
            return x

        jax.lax.pcast = pcast

    if not hasattr(jax.tree, "leaves_with_path"):
        jax.tree.leaves_with_path = jax.tree_util.tree_leaves_with_path
    if not hasattr(jax.tree, "flatten_with_path"):
        jax.tree.flatten_with_path = jax.tree_util.tree_flatten_with_path

    if not hasattr(jax.lax, "axis_size"):
        def axis_size(axis_name):
            # psum of a literal constant-folds to the (static, int) size of
            # the named axis — the documented pre-axis_size idiom.
            return jax.lax.psum(1, axis_name)

        jax.lax.axis_size = axis_size

    if "axis_types" not in inspect.signature(jax.make_mesh).parameters:
        _make_mesh = jax.make_mesh

        @functools.wraps(_make_mesh)
        def make_mesh(axis_shapes, axis_names, *, devices=None,
                      axis_types=None):
            # pre-0.5 meshes are implicitly all-Auto; reject an explicit
            # request for anything else rather than silently honoring it.
            if axis_types is not None and any(
                    t != jax.sharding.AxisType.Auto for t in axis_types):
                raise NotImplementedError(
                    "this jax only supports Auto mesh axes")
            return _make_mesh(axis_shapes, axis_names, devices=devices)

        jax.make_mesh = make_mesh


_install()
