"""Tuner-backed resolution for kernel block shapes and the LM loss path.

The single choke point the kernels and launchers consult when a block
argument is left at its 0 sentinel (``flash_attention``,
``pallas_lm_cross_entropy``) or the loss-path flags are unset
(``flags.resolve_lm_loss``). Resolution order:

1. explicit caller values — always win; when they override a MEASURED
   winner at the consulted shape a warning names both (once per process
   per shape, so a sweep harness doesn't drown in it);
2. the nearest banked winner from the cache store
   (``KERNEL_TUNE.local.json`` shadowing the committed
   ``KERNEL_TUNE.json`` — see :mod:`dtf_tpu.tune.cache`);
3. the built-in defaults (the round-5 sweep picks, same values the
   kernels carried as literals before the tuner existed).

Every resolve is process-cached (``lru_cache``): kernels call this
inside jit traces and a cache-file re-read per call would be absurd.
The cached plan is a plain frozen dataclass of ints — resolving twice
returns the identical object, so resolver lookups can never perturb a
traced program or retrace an AOT one (pinned by
tests/test_tune.py::test_resolver_never_retraces).

jax-free at module level; callers pass backend/n_devices in.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

from dtf_tpu.tune import cache as _cache

# Built-in fallbacks — the round-5 on-chip sweep picks (see
# ops/flash_attention.py and ops/fused_ce.py for the measurement
# provenance). The committed KERNEL_TUNE.json carries the same values
# WITH their measured rows; these literals only fire when both cache
# files are missing or stale.
FALLBACK_BLOCK_Q = 512
FALLBACK_BLOCK_K = 1024
FALLBACK_BLOCK_N = 512
FALLBACK_BLOCK_V = 1024
FALLBACK_SOURCE = "builtin-default (no kernel-tune cache entry)"


@dataclasses.dataclass(frozen=True)
class FlashPlan:
    block_q: int
    block_k: int
    block_h: int
    #: 0 = no banked backward winner: inherit the forward blocks (the
    #: pre-tuner contract of ``flash_attention``'s custom_vjp).
    block_q_bwd: int
    block_k_bwd: int
    source: str
    measured: bool


@dataclasses.dataclass(frozen=True)
class FusedCePlan:
    block_n: int
    block_v: int
    source: str
    measured: bool


@dataclasses.dataclass(frozen=True)
class LossPathPlan:
    #: "monolithic" | "chunk_tokens" | "chunk_vocab" | "pallas"
    path: str
    chunk: int
    source: str
    measured: bool


#: speculative-decode draft width when no winner is banked: proposals are
#: cheap relative to a verify pass and acceptance decays with depth, so a
#: mid-size default loses little either way (the bench_decode draft-k
#: sweep banks the measured per-(model, draft, slots) winner over it).
FALLBACK_SPEC_K = 4


@dataclasses.dataclass(frozen=True)
class SpecKPlan:
    k: int
    source: str
    measured: bool


#: matmul precision when no winner is banked: bf16 — the status-quo
#: numerics. Low precision only ever turns ON from banked data (a row
#: that beat bf16 on time AND passed the rel-err ceiling at selection,
#: ``search.select_precision_winner``) or an explicit pin.
FALLBACK_PRECISION = "bf16"


@dataclasses.dataclass(frozen=True)
class PrecisionPlan:
    #: "bf16" | "int8" | "fp8" — what tp_dense should actually run.
    precision: str
    source: str
    measured: bool


@functools.lru_cache(maxsize=1024)
def flash_plan(*, seq: int, heads: int, head_dim: int, dtype: str,
               causal: bool, window: int, n_devices: int = 1,
               backend: Optional[str] = None) -> FlashPlan:
    """The tuned flash block shapes for one attention shape."""
    key = dict(seq=seq, heads=heads, head_dim=head_dim, dtype=dtype,
               causal=causal, window=window, n_devices=n_devices,
               backend=backend)
    store = _cache.load_store()
    fwd = store.lookup("flash_fwd", key)
    bwd = store.lookup("flash_bwd", key)
    bq = bk = bh = 0
    src, measured = FALLBACK_SOURCE, False
    if fwd is not None:
        bq = int(fwd.winner.get("block_q", 0))
        bk = int(fwd.winner.get("block_k", 0))
        bh = int(fwd.winner.get("block_h", 1))
        src, measured = fwd.source, fwd.measured
    bqb = bkb = 0
    if bwd is not None:
        bqb = int(bwd.winner.get("block_q_bwd", 0))
        bkb = int(bwd.winner.get("block_k_bwd", 0))
    if bh < 1 or (heads and heads % bh):
        bh = 1   # a banked fold from a different head count must not
        # turn into a wrapper ValueError — clamp to the proven kernel
    return FlashPlan(block_q=bq or FALLBACK_BLOCK_Q,
                     block_k=bk or FALLBACK_BLOCK_K,
                     block_h=bh or 1,
                     block_q_bwd=bqb, block_k_bwd=bkb,
                     source=src, measured=measured)


@functools.lru_cache(maxsize=1024)
def fused_ce_plan(*, vocab: int, d_model: int, dtype: str,
                  n_devices: int = 1,
                  backend: Optional[str] = None) -> FusedCePlan:
    """The tuned Pallas fused-CE tile shape for one head shape."""
    key = dict(vocab=vocab, d_model=d_model, dtype=dtype,
               n_devices=n_devices, backend=backend)
    e = _cache.load_store().lookup("fused_ce", key)
    if e is None:
        return FusedCePlan(FALLBACK_BLOCK_N, FALLBACK_BLOCK_V,
                           FALLBACK_SOURCE, False)
    return FusedCePlan(
        block_n=int(e.winner.get("block_n", 0)) or FALLBACK_BLOCK_N,
        block_v=int(e.winner.get("block_v", 0)) or FALLBACK_BLOCK_V,
        source=e.source, measured=e.measured)


@functools.lru_cache(maxsize=256)
def lm_loss_winner(*, fits: bool, vocab: int, seq: int, batch: int,
                   n_devices: int = 1,
                   backend: Optional[str] = None
                   ) -> Optional[LossPathPlan]:
    """The banked LM loss-path winner for a (fits, shape) bucket, or
    None when nothing is banked (``flags.resolve_lm_loss`` then applies
    its HBM heuristic unchanged)."""
    key = dict(fits=fits, vocab=vocab, seq=seq, batch=batch,
               n_devices=n_devices, backend=backend)
    e = _cache.load_store().lookup("lm_loss", key)
    if e is None or "path" not in e.winner:
        return None
    return LossPathPlan(path=str(e.winner["path"]),
                        chunk=int(e.winner.get("chunk", 0)),
                        source=e.source, measured=e.measured)


@functools.lru_cache(maxsize=256)
def spec_k_plan(*, model: str, draft: str, n_slots: int,
                backend: Optional[str] = None) -> SpecKPlan:
    """The tuned speculative draft width for one (model, draft, slots)
    serving shape — ``DecodeEngine``'s 0-sentinel ``spec_k`` resolves
    here; an explicit ``--spec_k`` wins with a warn-once when it
    overrides a measured winner (``note_override``). Model/draft are
    architecture labels (hard-matched: a k measured for one pair never
    resolves for another); ``n_slots`` is soft (nearest batch)."""
    key = dict(model=model, draft=draft, n_slots=n_slots, backend=backend)
    e = _cache.load_store().lookup("spec_k", key)
    if e is None or "k" not in e.winner:
        return SpecKPlan(FALLBACK_SPEC_K, FALLBACK_SOURCE, False)
    return SpecKPlan(k=int(e.winner["k"]), source=e.source,
                     measured=e.measured)


@functools.lru_cache(maxsize=512)
def matmul_precision_plan(*, parallel: str, d_in: int, d_out: int,
                          dtype: str, n_devices: int = 1,
                          backend: Optional[str] = None) -> PrecisionPlan:
    """The tuned compute precision for one ``tp_dense`` projection site —
    ``precision='auto'`` resolves here; an explicit ``--matmul_precision``
    wins with a warn-once when it overrides a measured winner
    (``ops/quant.resolve_precision`` calls ``note_override``).

    ``site``/``parallel`` are hard-matched (a winner measured for the
    column ring never resolves for the row ring — different error
    model); d_in/d_out are soft (nearest shape), dtype adds the usual
    small penalty. The quality bound is enforced at SELECTION time
    (``search.select_precision_winner`` drops rows whose banked rel-err
    exceeds the ceiling), so any entry that resolves here already passed
    it — the plan just reports the winner."""
    key = dict(site="tp_dense", parallel=parallel, d_in=d_in, d_out=d_out,
               dtype=dtype, n_devices=n_devices, backend=backend)
    e = _cache.load_store().lookup("matmul_precision", key)
    if e is None or "precision" not in e.winner:
        return PrecisionPlan(FALLBACK_PRECISION, FALLBACK_SOURCE, False)
    return PrecisionPlan(precision=str(e.winner["precision"]),
                         source=e.source, measured=e.measured)


@functools.lru_cache(maxsize=256)
def _warn_override_once(kind: str, what: str, explicit: str,
                        winner: str, source: str) -> None:
    try:
        from absl import logging as absl_logging

        absl_logging.warning(
            "explicit %s %s=%s overrides the measured kernel-tune "
            "winner %s (%s); drop the explicit value to track the "
            "banked optimum, or re-sweep with scripts/bench_tune.py "
            "if the shape changed", kind, what, explicit, winner, source)
    except Exception:  # pragma: no cover
        pass


def note_override(kind: str, what: str, explicit, winner, *,
                  source: str, measured: bool) -> None:
    """Warn (once per distinct override) when an explicit value beats a
    measured winner. Policy-seeded (measured=False) entries never warn —
    overriding a guess is not a finding."""
    if measured and explicit != winner:
        _warn_override_once(kind, what, str(explicit), str(winner), source)


def _clear_plans() -> None:
    flash_plan.cache_clear()
    fused_ce_plan.cache_clear()
    lm_loss_winner.cache_clear()
    spec_k_plan.cache_clear()
    matmul_precision_plan.cache_clear()
    _warn_override_once.cache_clear()


# every store invalidation (including cache.merge_entries writes) must
# drop the memoized plans too, or a same-process bank-then-resolve
# serves pre-merge winners; registered once at import.
_cache.on_invalidate(_clear_plans)


def invalidate() -> None:
    """Drop every resolver/process cache (tests plant cache files via
    DTF_KERNEL_TUNE_PATH/_GOLDEN and re-resolve)."""
    _cache.invalidate_cache()     # store + registered plan caches
