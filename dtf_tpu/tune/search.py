"""Candidate spaces, deterministic winner selection, artifact seeding.

The WRITE side of the tuner: ``scripts/bench_tune.py`` measures the
candidate grids below on chip and banks winners through
:func:`select_winner`; :func:`seed_entries` re-derives the committed
``KERNEL_TUNE.json`` golden from the sweep artifacts already in the
repo (ATTN_BENCH.json block sweeps, BENCH_LM_SWEEP.json loss rows) so a
round that only banks raw rows — the sentinel's job — still flips
defaults the moment ``python -m dtf_tpu.tune seed`` (or bench_tune
itself, which runs the selection step even against a dead tunnel) is
run. No hand-transcription of winners into literals, ever again.

Winner selection is DETERMINISTIC on purpose: min metric, ties broken
by the canonical JSON of the candidate params — two runs over the same
rows bank the same winner, and tests inject synthetic timings to pin
the ordering (tests/test_tune.py).

How a new kernel registers candidates: add a ``<kind>_candidates()``
grid here, give the kernel a 0-sentinel block argument resolved through
a :mod:`dtf_tpu.tune.resolver` plan, teach ``bench_tune.py`` to time
the grid, and extend :func:`seed_entries` if its rows land in a
committed artifact (docs/TUNING.md walks an example).

jax-free at module level (package discipline).
"""

from __future__ import annotations

import json
import os
from typing import Optional

from dtf_tpu.tune.cache import Entry

#: forward block grid (the round-5 sweep's shapes): square vs
#: rectangular vs doubled-k, the axes that moved the needle on v5e.
FLASH_FWD_CANDIDATES = ((256, 256), (512, 512), (512, 1024), (1024, 512),
                        (1024, 1024), (512, 2048))
#: backward grid (fwd pinned at its winner): the _dq/_dkv kernels stream
#: the opposite extents from the forward, so the optimum may differ —
#: (512, 1024) repeats the inherited default as a same-window control.
FLASH_BWD_CANDIDATES = ((512, 512), (1024, 512), (512, 1024),
                        (1024, 1024), (256, 1024))
#: fused-CE tile grid: token-block x vocab-block around the 512x1024
#: default (VMEM bound ~8 MB at D<=1024 — fused_ce.py docstring).
FUSED_CE_CANDIDATES = ((256, 1024), (512, 512), (512, 1024), (512, 2048),
                       (1024, 1024))
#: LM loss paths A/B'd by bench_tune (chunk values are the banked sweep
#: shapes: AUTO_LOSS_CHUNK_TOKENS / the vocab ladder's 8192).
LM_LOSS_CANDIDATES = (("monolithic", 0), ("chunk_tokens", 4096),
                      ("chunk_vocab", 8192), ("pallas", 0))
#: the tp_dense precision axis bench_quant A/Bs per (parallel, shape)
#: site. bf16 is the control every row is judged against; fp8 rows only
#: run where the jax carries the e4m3 dtype (quant.fp8_supported).
MATMUL_PRECISION_CANDIDATES = ("bf16", "int8", "fp8")
#: quality ceiling a low-precision row must beat to be ELIGIBLE as a
#: winner: Frobenius rel-err of the quantized projection output vs the
#: bf16 control on the same seeded operands. 5e-2 is deliberately loose
#: — per-channel symmetric int8 on activation-scale data lands ~1e-2;
#: a row near the ceiling signals an outlier-heavy shape where low
#: precision should NOT win (docs/TUNING.md "Precision winners").
PRECISION_REL_ERR_CEILING = 5e-2


def flash_fwd_candidates(seq: int) -> list[tuple[int, int]]:
    """The fwd grid clamped to the sequence (a block wider than T just
    re-measures the T-sized clamp the wrapper applies)."""
    out, seen = [], set()
    for bq, bk in FLASH_FWD_CANDIDATES:
        c = (min(bq, seq), min(bk, seq))
        if c not in seen:
            seen.add(c)
            out.append(c)
    return out


def flash_bwd_candidates(seq: int) -> list[tuple[int, int]]:
    out, seen = [], set()
    for bq, bk in FLASH_BWD_CANDIDATES:
        c = (min(bq, seq), min(bk, seq))
        if c not in seen:
            seen.add(c)
            out.append(c)
    return out


def select_winner(rows: list[dict], *, metric: str,
                  lower_is_better: bool = True) -> Optional[dict]:
    """The winning row: best ``metric``, deterministic tie-break.

    Rows missing the metric (a child that died mid-sweep) are skipped;
    an empty field → None (caller keeps the previous winner). Ties
    break on the canonical JSON of the row so injected-equal timings
    still select reproducibly."""
    live = [r for r in rows
            if isinstance(r.get(metric), (int, float))]
    if not live:
        return None
    sign = 1.0 if lower_is_better else -1.0
    return min(live, key=lambda r: (sign * float(r[metric]),
                                    json.dumps(r, sort_keys=True)))


def select_precision_winner(rows: list[dict]) -> Optional[dict]:
    """The winning precision row for ONE (parallel, d_in, d_out) site:
    fastest ``matmul_s`` among rows that pass the quality bound.

    bf16 rows are exempt from the ceiling (they ARE the reference); a
    low-precision row missing its ``rel_err`` is dropped, not trusted —
    the bound is the whole point of tuner ownership."""
    eligible = []
    for r in rows:
        if r.get("precision") == "bf16":
            eligible.append(r)
            continue
        err = r.get("rel_err")
        if isinstance(err, (int, float)) and \
                float(err) <= PRECISION_REL_ERR_CEILING:
            eligible.append(r)
    return select_winner(eligible, metric="matmul_s")


# --------------------------------------------------------------- seeding


def _read_json(path: str) -> dict:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        return data if isinstance(data, dict) else {}
    except (OSError, json.JSONDecodeError, UnicodeDecodeError):
        return {}


def _attn_key(row: dict, backend: str = "tpu") -> dict:
    return dict(seq=int(row.get("seq", 0)), heads=int(row.get("h", 0)),
                head_dim=int(row.get("d", 0)),
                dtype=str(row.get("dtype", "bfloat16")), causal=True,
                window=0, n_devices=1, backend=backend)


#: bench_tune.py persists its raw on-chip sweep rows here (committed),
#: so the golden is ALWAYS re-derivable from artifacts — a re-seed
#: after a measuring round reproduces the measured winners instead of
#: reverting them to older data.
SWEEP_ARTIFACT = "KERNEL_TUNE_SWEEP.json"


def _shape_of(row: dict) -> tuple:
    return (int(row.get("seq", 0)), int(row.get("h", 0)),
            int(row.get("d", 0)), str(row.get("dtype", "bfloat16")))


def _is_bwd_row(row: dict) -> bool:
    return bool(row.get("block_q_bwd") or row.get("block_k_bwd"))


def seed_flash_entries(root: str) -> list[Entry]:
    """flash_fwd/flash_bwd winners per SHAPE from the banked sweeps:
    ATTN_BENCH.json's ``tpu.block_sweep`` / ``tpu.bwd_block_sweep``
    plus bench_tune's own persisted rows (KERNEL_TUNE_SWEEP.json).

    - fwd: min ``flash_fwd_s`` over the shape's fwd rows.
    - bwd: min ``flash_fwdbwd_s`` over the shape's STANDALONE bwd rows
      (block_q_bwd/block_k_bwd set, fwd pinned) when any exist;
      otherwise the shape's best fwd+bwd row seeds the INHERITED pair
      that measurement actually ran — so the default comes from data
      either way, and re-seeding after the sentinel banks the
      standalone rows flips it to the independent optimum automatically.
    """
    tpu = _read_json(os.path.join(root, "ATTN_BENCH.json")).get("tpu", {})
    rows = list((tpu.get("block_sweep") or {}).get("rows") or [])
    rows += list((tpu.get("bwd_block_sweep") or {}).get("rows") or [])
    rows += [r for r in _read_json(
        os.path.join(root, SWEEP_ARTIFACT)).get("rows", [])
        if r.get("backend") == "tpu"]
    shapes: dict[tuple, dict] = {}
    for r in rows:
        if not all(_shape_of(r)[:3]):
            continue
        g = shapes.setdefault(_shape_of(r), {"fwd": [], "bwd": []})
        g["bwd" if _is_bwd_row(r) else "fwd"].append(r)
    entries: list[Entry] = []
    for g in shapes.values():
        fwd = select_winner(g["fwd"], metric="flash_fwd_s")
        if fwd:
            entries.append(Entry(
                kind="flash_fwd", key=_attn_key(fwd),
                winner={"block_q": int(fwd["block_q"]),
                        "block_k": int(fwd["block_k"]),
                        "block_h": int(fwd.get("block_h", 1))},
                metric={"flash_fwd_s": fwd.get("flash_fwd_s"),
                        "flash_fwd_tflops": fwd.get("flash_fwd_tflops")},
                source=("banked fwd block-sweep rows (ATTN_BENCH.json / "
                        "KERNEL_TUNE_SWEEP.json, v5e)"),
                measured=True))
        if g["bwd"]:
            bwd = select_winner(g["bwd"], metric="flash_fwdbwd_s")
            if bwd:
                entries.append(Entry(
                    kind="flash_bwd", key=_attn_key(bwd),
                    winner={"block_q_bwd": int(bwd.get("block_q_bwd")
                                               or 0),
                            "block_k_bwd": int(bwd.get("block_k_bwd")
                                               or 0)},
                    metric={"flash_fwdbwd_s": bwd.get("flash_fwdbwd_s")},
                    source=("banked STANDALONE bwd block-sweep rows "
                            "(fwd pinned; ATTN_BENCH.json / "
                            "KERNEL_TUNE_SWEEP.json, v5e)"),
                    measured=True))
        elif fwd is not None:
            bwd = select_winner(g["fwd"], metric="flash_fwdbwd_s")
            if bwd:
                entries.append(Entry(
                    kind="flash_bwd", key=_attn_key(bwd),
                    winner={"block_q_bwd": int(bwd["block_q"]),
                            "block_k_bwd": int(bwd["block_k"])},
                    metric={"flash_fwdbwd_s": bwd.get("flash_fwdbwd_s")},
                    source=("banked fwd+bwd rows (bwd INHERITED the fwd "
                            "blocks in this measurement; the standalone "
                            "bwd sweep is queued — bench_attention "
                            "--sweep-blocks-bwd / bench_tune — and "
                            "re-seeding banks its independent optimum)"),
                    measured=True))
    return entries


def _lm_row_path(row: dict) -> tuple[str, int]:
    if row.get("loss_pallas"):
        return "pallas", 0
    if row.get("loss_chunk_tokens"):
        return "chunk_tokens", int(row["loss_chunk_tokens"])
    if row.get("loss_chunk"):
        return "chunk_vocab", int(row["loss_chunk"])
    return "monolithic", 0


def seed_lm_loss_entries(root: str) -> list[Entry]:
    """lm_loss winners per fits-bucket from the GPT sweep rows.

    Bucketing uses the same per-device HBM estimate as
    ``flags.resolve_lm_loss`` (logits + cotangent vs the budget
    fraction), so a banked winner lands in exactly the bucket the
    resolver will query. Within the fits=True bucket the data decides
    outright (round 5: monolithic 58.0%% vs vocab-chunk 48.9%%). In the
    fits=False bucket only the vocab scan is measured so far; the
    token-chunk A/B rides the bench_tune queue, and until it banks, the
    entry encodes the PERF.md §0b chunk-axis ordering (token chunking:
    one full-vocab MXU matmul per block vs the serialized vocab scan
    that costs ~9 MFU points) as a measured=False policy winner — the
    measured vocab rows are recorded as alternatives in the metric."""
    from dtf_tpu.cli.flags import (AUTO_LOSS_CHUNK_TOKENS,
                                   HBM_BYTES_PER_CHIP,
                                   LOGITS_HBM_FRACTION)

    raw = list(_read_json(
        os.path.join(root, "BENCH_LM_SWEEP.json")).get("rows", []))
    # bench_tune's own A/B rows (BENCH_LM.json "loss_path") join the
    # pool — newer rows land later and win ties deterministically only
    # via the canonical-JSON tie-break, but a real delta decides on data.
    raw += list((_read_json(os.path.join(root, "BENCH_LM.json"))
                 .get("loss_path") or {}).get("rows", []))
    rows = [r for r in raw
            if r.get("model") == "gpt" and r.get("phase", "step") == "step"
            and r.get("gpt_size", "small") == "small"]
    buckets: dict[bool, list[dict]] = {True: [], False: []}
    vocab = 50304   # the GPT flagship vocab (models/gpt.py)
    for r in rows:
        batch, seq = int(r.get("batch", 0)), int(r.get("seq", 0))
        if not (batch and seq):
            continue
        est = 2 * batch * seq * vocab * 4
        fits = est <= LOGITS_HBM_FRACTION * HBM_BYTES_PER_CHIP
        path, chunk = _lm_row_path(r)
        buckets[fits].append({
            "path": path, "chunk": chunk, "batch": batch, "seq": seq,
            "mfu": r.get("mfu_analytic"),
            "tokens_per_sec": r.get("tokens_per_sec")})
    entries: list[Entry] = []
    for fits, brows in buckets.items():
        if not brows:
            continue
        alts = {f"{b['path']}_b{b['batch']}": b["mfu"] for b in brows
                if isinstance(b.get("mfu"), (int, float))}
        rep = brows[0]
        key = dict(fits=fits, vocab=vocab, seq=rep["seq"],
                   batch=rep["batch"], n_devices=1, backend="tpu")
        best = select_winner(brows, metric="mfu", lower_is_better=False)
        paths = {b["path"] for b in brows}
        if fits or (best and best["path"] != "chunk_vocab") or \
                "chunk_tokens" in paths:
            if best is None:
                continue
            entries.append(Entry(
                kind="lm_loss", key=key,
                winner={"path": best["path"], "chunk": best["chunk"]},
                metric={"mfu": best["mfu"], "alternatives": alts},
                source=("BENCH_LM_SWEEP.json gpt rows (v5e, round 5): "
                        "best measured mfu_analytic in this fits bucket"),
                measured=True))
        else:
            # only the vocab scan is measured where logits don't fit:
            # bank the PERF-ordered token-chunk preference until the
            # bench_tune A/B replaces it with data.
            entries.append(Entry(
                kind="lm_loss", key=key,
                winner={"path": "chunk_tokens",
                        "chunk": AUTO_LOSS_CHUNK_TOKENS},
                metric={"alternatives": alts},
                source=("PERF.md §0b/§0c chunk-axis ordering (vocab "
                        "scan costs ~9 MFU points; token chunking is "
                        "one full-vocab MXU matmul per block). The "
                        "mono/token/pallas A/B rows ride bench_tune's "
                        "loss_path queue; re-seed after they bank."),
                measured=False))
    return entries


def seed_spec_k_entries(root: str) -> list[Entry]:
    """spec_k winners per (model, draft, slots, backend) from the serve
    sweep rows (``bench_decode --sweep-serve`` draft-k axis, merged under
    BENCH_LM.json "serve"): best GOODPUT tokens/sec among the swept k
    values on the same seeded arrivals. Rows carry the architecture
    labels the engine's resolver queries (``model_arch``/``draft_arch``
    — serve/engine.py ``_cfg_label``), so a banked winner lands exactly
    where ``DecodeEngine(spec_k=0)`` will look."""
    rows = list((_read_json(os.path.join(root, "BENCH_LM.json"))
                 .get("serve") or {}).get("rows", []))
    groups: dict[tuple, list[dict]] = {}
    for r in rows:
        k = int(r.get("spec_k", 0) or 0)
        if not k or not r.get("model_arch") or not r.get("draft_arch"):
            continue
        serve = r.get("serve") or {}
        if not isinstance(serve.get("tokens_per_sec"), (int, float)):
            continue
        slots = int(r.get("n_slots", 0)) // max(int(r.get("replicas", 1)
                                                    or 1), 1)
        gk = (str(r["model_arch"]), str(r["draft_arch"]), slots,
              str(r.get("backend", "tpu")))
        groups.setdefault(gk, []).append({
            "k": k, "tokens_per_sec": float(serve["tokens_per_sec"]),
            "accept_rate": serve.get("accept_rate")})
    entries: list[Entry] = []
    for (m, d, slots, backend), brows in sorted(groups.items()):
        best = select_winner(brows, metric="tokens_per_sec",
                             lower_is_better=False)
        if best is None:
            continue
        entries.append(Entry(
            kind="spec_k",
            key=dict(model=m, draft=d, n_slots=slots, backend=backend),
            winner={"k": int(best["k"])},
            metric={"tokens_per_sec": best["tokens_per_sec"],
                    "accept_rate": best.get("accept_rate"),
                    "alternatives": {f"k{b['k']}": b["tokens_per_sec"]
                                     for b in brows}},
            source=("BENCH_LM.json serve rows (bench_decode "
                    "--sweep-serve draft-k axis): best goodput on the "
                    "same seeded arrivals"),
            measured=True))
    return entries


def spec_policy_entries() -> list[Entry]:
    """The flagship (gpt2_small, gpt2_draft) spec_k default until the
    on-chip draft-k sweep banks: k=4 — acceptance on natural text decays
    with depth while verify cost grows with k+1, and 4 is the center of
    the swept grid (2/4/8). measured=False: the resolver uses it but an
    explicit --spec_k never warns about overriding a guess."""
    return [Entry(
        kind="spec_k",
        key=dict(model="d768L12h12kv12v50304",     # gpt2_small
                 draft="d384L3h6kv6v50304",        # gpt2_draft
                 n_slots=8, backend="tpu"),
        winner={"k": 4},
        source=("policy default pending the queued bench_decode "
                "--sweep-serve draft-k rows (re-seed after they bank)"),
        measured=False)]


def seed_precision_entries(root: str) -> list[Entry]:
    """matmul_precision winners per (parallel, d_in, d_out, dtype) site
    from the banked bench_quant rows (KERNEL_TUNE_SWEEP.json
    ``precision_rows``): fastest ``matmul_s`` among rows inside the
    rel-err ceiling — a site where nothing beats bf16 banks bf16, which
    is itself useful data (``--matmul_precision=int8`` there warns)."""
    rows = [r for r in _read_json(
        os.path.join(root, SWEEP_ARTIFACT)).get("precision_rows", [])
        if r.get("parallel") and r.get("d_in") and r.get("d_out")]
    groups: dict[tuple, list[dict]] = {}
    for r in rows:
        gk = (str(r["parallel"]), int(r["d_in"]), int(r["d_out"]),
              str(r.get("dtype", "bfloat16")),
              str(r.get("backend", "tpu")), int(r.get("n_devices", 1)))
        groups.setdefault(gk, []).append(r)
    entries: list[Entry] = []
    for (parallel, d_in, d_out, dtype, backend, n_dev), brows in \
            sorted(groups.items()):
        best = select_precision_winner(brows)
        if best is None:
            continue
        entries.append(Entry(
            kind="matmul_precision",
            key=dict(site="tp_dense", parallel=parallel, d_in=d_in,
                     d_out=d_out, dtype=dtype, n_devices=n_dev,
                     backend=backend),
            winner={"precision": str(best["precision"]),
                    "rel_err": best.get("rel_err")},
            metric={"matmul_s": best.get("matmul_s"),
                    "alternatives": {
                        str(b["precision"]): b.get("matmul_s")
                        for b in brows}},
            source=("banked bench_quant precision rows "
                    "(KERNEL_TUNE_SWEEP.json precision_rows): fastest "
                    "matmul_s inside the rel-err ceiling "
                    f"({PRECISION_REL_ERR_CEILING:g})"),
            measured=True))
    return entries


def precision_policy_entries() -> list[Entry]:
    """The quantized-DRAFT serving default until the on-chip precision
    sweep banks: int8 at the gpt2_draft projection widths (384<->1536).
    The draft's output never reaches a user — the bf16 verifier owns
    the emitted token stream byte-for-byte (tests/test_serve_spec.py) —
    so a draft-side quality miss costs only acceptance rate, never
    correctness; that asymmetry is why the draft gets the first
    low-precision win. measured=False: an explicit --draft_precision
    never warns about overriding a guess, and the next bench_quant
    round replaces these with timed rows at the same keys."""
    src = ("policy default pending the queued bench_quant precision "
           "rows (draft-side only: the bf16 verifier keeps emitted "
           "tokens byte-identical; re-seed after rows bank)")

    def _e(parallel, d_in, d_out):
        return Entry(
            kind="matmul_precision",
            key=dict(site="tp_dense", parallel=parallel, d_in=d_in,
                     d_out=d_out, dtype="bfloat16", n_devices=1,
                     backend="tpu"),
            winner={"precision": "int8"}, source=src, measured=False)

    # gpt2_draft (d384, ff1536): qkv/attn-proj 384x384 column,
    # mlp_in 384x1536 column, attn_out/mlp_out row back into d_model.
    return [_e("column", 384, 384), _e("column", 384, 1536),
            _e("row", 384, 384), _e("row", 1536, 384)]


def cpu_sim_fallback_entries() -> list[Entry]:
    """Deterministic CPU-sim entries mirroring the built-in defaults.

    Interpret-mode timings are not MXU-predictive, so the CPU sim
    should resolve like the chip does — nearest-shape lookup already
    lands on the banked tpu winners; these entries exist so a tree with
    a pruned tpu section still resolves deterministically (and so tests
    have a stable backend='cpu' row to assert against)."""
    src = ("cpu_sim_fallback: mirrors the built-in defaults — "
           "interpret-mode timing is not predictive of the MXU")
    return [
        Entry(kind="flash_fwd",
              key=dict(seq=1024, heads=12, head_dim=64, dtype="bfloat16",
                       causal=True, window=0, n_devices=8, backend="cpu"),
              winner={"block_q": 512, "block_k": 1024, "block_h": 1},
              source=src, measured=False),
        Entry(kind="fused_ce",
              key=dict(vocab=50304, d_model=768, dtype="bfloat16",
                       n_devices=8, backend="cpu"),
              winner={"block_n": 512, "block_v": 1024},
              source=src, measured=False),
    ]


def seed_entries(root: Optional[str] = None) -> list[Entry]:
    """Everything the committed artifacts support, in one list."""
    from dtf_tpu.tune.cache import repo_root

    root = root or repo_root()
    # policy entries FIRST: merge_entries is last-wins per canonical key,
    # so a measured spec_k row banking at the policy's exact key replaces
    # the guess instead of being shadowed by it.
    return (spec_policy_entries() + precision_policy_entries()
            + seed_flash_entries(root) + seed_lm_loss_entries(root)
            + seed_spec_k_entries(root) + seed_precision_entries(root)
            + cpu_sim_fallback_entries())
