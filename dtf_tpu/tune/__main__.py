"""CLI: (re)seed the committed golden / inspect a resolution.

    python -m dtf_tpu.tune seed            # artifacts -> KERNEL_TUNE.json
    python -m dtf_tpu.tune seed --local    # -> KERNEL_TUNE.local.json
    python -m dtf_tpu.tune show --seq=1024 --heads=12 --head-dim=64

One JSON line on stdout (the bench.py idiom); exit 0 unless the
arguments are unusable. No jax anywhere — this must run on a machine
whose tunnel is down.
"""

from __future__ import annotations

import json
import sys

from dtf_tpu.tune import cache, resolver, search


def _arg(argv, name, default=None):
    pre = f"--{name}="
    for a in argv:
        if a.startswith(pre):
            return a[len(pre):]
    return default


def main(argv: list[str]) -> int:
    if not argv or argv[0] not in ("seed", "show"):
        print(json.dumps({"error": "usage: python -m dtf_tpu.tune "
                          "seed [--local] | show [--seq=..] [--heads=..] "
                          "[--head-dim=..] [--dtype=..] [--backend=..]"}))
        return 2
    if argv[0] == "seed":
        root = _arg(argv, "root") or cache.repo_root()
        entries = search.seed_entries(root)
        path = (cache.local_path() if "--local" in argv
                else cache.golden_path())
        total = cache.merge_entries(path, entries,
                                    generated_by="python -m dtf_tpu.tune "
                                    "seed")
        print(json.dumps({
            "seeded": len(entries), "total_entries": total, "path": path,
            "kinds": sorted({e.kind for e in entries}),
            "winners": {e.canonical_key(): e.winner for e in entries}},
            sort_keys=True))
        return 0
    # show: resolve one flash shape + the fused-CE/loss-path buckets
    seq = int(_arg(argv, "seq", "1024"))
    heads = int(_arg(argv, "heads", "12"))
    head_dim = int(_arg(argv, "head-dim", "64"))
    dtype = _arg(argv, "dtype", "bfloat16")
    backend = _arg(argv, "backend")
    plan = resolver.flash_plan(seq=seq, heads=heads, head_dim=head_dim,
                               dtype=dtype, causal=True, window=0,
                               backend=backend)
    ce = resolver.fused_ce_plan(vocab=int(_arg(argv, "vocab", "50304")),
                                d_model=heads * head_dim, dtype=dtype,
                                backend=backend)
    out = {"flash": plan.__dict__, "fused_ce": ce.__dict__,
           "golden": cache.golden_path(), "local": cache.local_path()}
    for fits in (True, False):
        w = resolver.lm_loss_winner(
            fits=fits, vocab=int(_arg(argv, "vocab", "50304")), seq=seq,
            batch=int(_arg(argv, "batch", "8")), backend=backend)
        out[f"lm_loss_fits_{fits}"] = None if w is None else w.__dict__
    print(json.dumps(out, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
