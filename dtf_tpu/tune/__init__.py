"""Kernel autotuner: persistent block-shape / loss-path winners.

PERF.md round 5 closed with every remaining MFU lever measured but
hand-tuned: the on-chip block sweep showed 512x1024 flash blocks run the
same attention 2.75x faster than the old 512x512 default, the backward
runs ~92 TF/s against the forward's ~170 with its own (separately
swept) block optimum, and the loss-path data says the monolithic
[B,T,V] matmul wins while it fits and token chunking is the right
bounded-memory fallback. Each of those findings used to be flipped into
a hard-coded literal by hand each round. This package is the mechanism
that does it automatically — the same static-search-then-pin discipline
the pjit-TPUv4 work applies to sharding (PAPERS.md, arxiv 2204.06514):

- :mod:`dtf_tpu.tune.cache` — the persistent winner store: a committed
  repo golden ``KERNEL_TUNE.json`` (banked on-chip winners, survives
  tunnel-down rounds) shadowed by a machine-local
  ``KERNEL_TUNE.local.json`` next to ``.jax_cache/`` (winners measured
  on THIS machine, gitignored), with nearest-shape lookup so a query at
  an unswept shape resolves to the closest banked winner instead of a
  hard-coded literal.
- :mod:`dtf_tpu.tune.search` — the candidate spaces, the deterministic
  winner selection, and the artifact seeding that turns the committed
  sweep rows (ATTN_BENCH.json block sweeps, BENCH_LM_SWEEP.json loss
  rows) into golden entries.
- :mod:`dtf_tpu.tune.resolver` — the read side consumed by the kernels
  and launchers: ``flash_attention`` / ``pallas_lm_cross_entropy``
  resolve 0-valued block args here, ``flags.resolve_lm_loss`` resolves
  the LM loss path here. Explicit values still win (with a warning when
  they override a measured winner).

``scripts/bench_tune.py`` is the write side: probe-first, watchdogged,
queued in ``tpu_pipeline.sh`` before the LM benches so their rows are
measured at tuned defaults. The whole package is jax-free at module
level (the telemetry/ discipline): resolution must work on a backendless
machine and must never be the thing that hangs against a dead tunnel.

Docs: docs/TUNING.md.
"""

from dtf_tpu.tune.cache import (Entry, TuneStore, golden_path,  # noqa: F401
                                invalidate_cache, load_store, local_path,
                                merge_entries)
from dtf_tpu.tune.resolver import (FlashPlan, FusedCePlan,  # noqa: F401
                                   LossPathPlan, flash_plan, fused_ce_plan,
                                   invalidate, lm_loss_winner)
