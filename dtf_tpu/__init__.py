"""dtf_tpu — a TPU-native distributed training framework.

A ground-up JAX/XLA/pjit/Pallas re-design of the capability surface of the
reference repo ``zjj2wry/distributed-tensorflow`` (a TF1 parameter-server /
worker training harness; see SURVEY.md for the full structural analysis).

The reference's ps/worker roles collapse into a single pjit'd train step over
a TPU device mesh:

- variable placement (``tf.device('/job:ps')`` + ``replica_device_setter``)
  → GSPMD ``NamedSharding`` over a named mesh       → :mod:`dtf_tpu.core.mesh`,
    :mod:`dtf_tpu.core.sharding`
- gradient aggregation (``SyncReplicasOptimizer``) → mean-gradients via XLA
  all-reduce over ICI                               → :mod:`dtf_tpu.core.train`
- ``MonitoredTrainingSession`` hooks (checkpoint / summary / recovery)
  → Orbax + metric writers + a hook-driven loop     → :mod:`dtf_tpu.loop`,
    :mod:`dtf_tpu.checkpoint`, :mod:`dtf_tpu.metrics`
- ``ClusterSpec`` / ``tf.train.Server`` bootstrap   → ``jax.distributed`` +
  mesh construction                                 → :mod:`dtf_tpu.core.dist`
"""

__version__ = "0.1.0"

try:
    import jax as _jax  # noqa: F401
except ImportError:
    # Backend-less machine: the training/serving stack is unusable, but
    # dtf_tpu.telemetry's XPlane parser and report CLI must still import
    # (traces are captured on a chip and analyzed wherever convenient —
    # the srclint lazy-import fence keeps those modules jax/tf-free, and
    # tests/test_analysis.py proves the no-backend import path works).
    HAVE_JAX = False
else:
    HAVE_JAX = True
    from dtf_tpu import _jax_compat  # noqa: F401  (backfills jax.shard_map etc.)
    from dtf_tpu.core.mesh import MeshConfig, make_mesh, AXIS_DATA, AXIS_SEQ, AXIS_MODEL  # noqa: F401,E501
