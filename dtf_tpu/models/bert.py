"""BERT-base MLM pretraining — BASELINE config 4 (grad-accum + ZeRO-1).

The reference never shipped a BERT, but the capability row demands it:
"BERT-base pretraining (grad-accum + ZeRO-1 optimizer-state sharding)". This
is a from-scratch flax encoder designed for the mesh:

- Megatron-style TP over the ``model`` axis (qkv/mlp-in column-sharded,
  attn-out/mlp-out row-sharded, embeddings vocab-sharded) via
  :data:`tp_rules` — the GSPMD successor of PS-sharded variables.
- Sequence/context parallelism over the ``seq`` axis via ring attention
  (:func:`dtf_tpu.ops.attention.ring_attention_sharded`).
- MLM loss through the one-hot sharded cross-entropy
  (:func:`dtf_tpu.ops.losses.softmax_cross_entropy`) so vocab-sharded logits
  never need a sharded gather.
- bf16 compute, f32 params/layernorms; post-LN like original BERT.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh, PartitionSpec as P

from dtf_tpu.core import comms
from dtf_tpu.core.train import LossAux
from dtf_tpu.ops import attention as att
from dtf_tpu.ops import flash_attention as fa
from dtf_tpu.ops.losses import softmax_cross_entropy




@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden: int = 768
    layers: int = 12
    heads: int = 12
    intermediate: int = 3072
    max_positions: int = 512
    type_vocab: int = 2
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.bfloat16
    #: attention backend for the non-seq-sharded path: auto (flash kernel on
    #: TPU, dense elsewhere) | dense | flash. Seq sharding always rings.
    attn_impl: str = "auto"
    #: latency-hiding collective matmul for the TP projections (q/k/v +
    #: attn_out, mlp_in/mlp_out) — same semantics as
    #: :attr:`dtf_tpu.models.gpt.GPTConfig.tp_overlap` (docs/OVERLAP.md).
    tp_overlap: bool = False

    @staticmethod
    def base() -> "BertConfig":
        return BertConfig()

    @staticmethod
    def tiny(**kw) -> "BertConfig":
        return BertConfig(vocab_size=128, hidden=32, layers=2, heads=4,
                          intermediate=64, max_positions=64, dropout=0.0, **kw)


def effective_attn_impl(impl: str, seq_sharded: bool) -> str:
    """Resolve the attention dispatch exactly as :class:`SelfAttention`
    does: a seq axis > 1 ALWAYS routes to the seq-sharded ring (the
    ``--attn_impl`` flag only controls the non-seq-sharded backend);
    otherwise ``auto`` means flash on TPU, dense elsewhere.

    THE single source of truth for the dispatch: launchers call this to
    decide ``--grad_shard`` viability (everything but ``dense`` runs in a
    shard_map the per-shard-group vmap cannot nest — docs/ZERO.md), so a
    dispatch change here cannot drift from the blocker logic.
    """
    if seq_sharded:
        return "ring"
    if impl != "auto":
        return impl
    return "flash" if jax.default_backend() == "tpu" else "dense"


#: Megatron-style TP placement over the `model` mesh axis (SURVEY.md §2c TP).
tp_rules = [
    (r"token_embed/embedding", P("model", None)),       # vocab-sharded rows
    (r"(query|key|value)/kernel", P(None, "model")),    # column parallel
    (r"attn_out/kernel", P("model", None)),             # row parallel
    (r"mlp_in/kernel", P(None, "model")),
    (r"mlp_out/kernel", P("model", None)),
    (r"(query|key|value|mlp_in)/bias", P("model")),
    (r"mlm_dense/kernel", P(None, "model")),
    (r"mlm_bias", P("model")),
]


class SelfAttention(nn.Module):
    cfg: BertConfig
    mesh: Optional[Mesh]

    @nn.compact
    def __call__(self, x, pad_mask, deterministic: bool):
        cfg = self.cfg
        d_head = cfg.hidden // cfg.heads
        # comms.TpDense is a drop-in nn.Dense (identical param tree); under
        # --tp_overlap the projections become collective matmuls, otherwise
        # its dispatch is the plain einsum.
        overlap = cfg.tp_overlap and self.mesh is not None
        dense = lambda name: comms.TpDense(  # noqa: E731
            cfg.hidden, self.mesh, "column", overlap=overlap,
            dtype=cfg.dtype, name=name)
        # [B,T,Hd] → [B,H,T,D]
        def split(t):
            return t.reshape(t.shape[0], t.shape[1], cfg.heads,
                             d_head).transpose(0, 2, 1, 3)

        q, k, v = (split(dense(n)(x)) for n in ("query", "key", "value"))
        seq_sharded = (self.mesh is not None
                       and self.mesh.shape.get("seq", 1) > 1)
        impl = effective_attn_impl(cfg.attn_impl, seq_sharded)
        if seq_sharded:
            # context parallelism: ring attention over the seq axis; the pad
            # mask rides the ring with K/V so padded keys are excluded
            # exactly as in the dense path.
            out = att.ring_attention_sharded(q, k, v, self.mesh,
                                             kv_mask=pad_mask)
        else:
            if impl == "flash":
                # fused kernel with the padding mask riding as a -inf bias
                # row; batch over data, heads over model, seq whole/shard.
                out = fa.flash_attention_sharded(
                    q, k, v, self.mesh, kv_mask=pad_mask,
                    interpret=jax.default_backend() != "tpu")
            elif impl == "dense":
                bias = jnp.where(pad_mask[:, None, None, :], 0.0, -jnp.inf)
                out = att.dense_attention(q, k, v, bias=bias)
            else:
                # a typo'd --attn_impl must not silently train dense
                raise ValueError(
                    f"unknown attn_impl {impl!r} (auto | dense | flash)")
        out = out.transpose(0, 2, 1, 3).reshape(x.shape[0], x.shape[1],
                                                cfg.hidden)
        out = comms.TpDense(cfg.hidden, self.mesh, "row", overlap=overlap,
                            dtype=cfg.dtype, name="attn_out")(out)
        out = nn.Dropout(cfg.dropout)(out, deterministic=deterministic)
        return out


class EncoderLayer(nn.Module):
    cfg: BertConfig
    mesh: Optional[Mesh]

    @nn.compact
    def __call__(self, x, pad_mask, deterministic: bool):
        cfg = self.cfg
        overlap = cfg.tp_overlap and self.mesh is not None
        a = SelfAttention(cfg, self.mesh, name="attention")(
            x, pad_mask, deterministic)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_attn")(x + a)
        if overlap:
            # hold the Megatron-SP token-sharded layout through the
            # post-LN residual points (comms.tp_token_sharded docstring)
            x = comms.tp_token_sharded(x, self.mesh)
        h = comms.TpDense(cfg.intermediate, self.mesh, "column",
                          overlap=overlap, dtype=cfg.dtype,
                          name="mlp_in")(x)
        h = nn.gelu(h, approximate=True)
        h = comms.TpDense(cfg.hidden, self.mesh, "row", overlap=overlap,
                          dtype=cfg.dtype, name="mlp_out")(h)
        h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        out = nn.LayerNorm(dtype=jnp.float32, name="ln_mlp")(x + h)
        return comms.tp_token_sharded(out, self.mesh) if overlap else out


class BertMLM(nn.Module):
    """Encoder + MLM head (decoder tied to the token embedding)."""

    cfg: BertConfig
    mesh: Optional[Mesh] = None

    @nn.compact
    def __call__(self, input_ids, segment_ids, pad_mask, *,
                 deterministic: bool = True, return_hidden: bool = False):
        cfg = self.cfg
        tok = nn.Embed(cfg.vocab_size, cfg.hidden, dtype=cfg.dtype,
                       param_dtype=jnp.float32, name="token_embed")
        pos = nn.Embed(cfg.max_positions, cfg.hidden, dtype=cfg.dtype,
                       param_dtype=jnp.float32, name="pos_embed")
        seg = nn.Embed(cfg.type_vocab, cfg.hidden, dtype=cfg.dtype,
                       param_dtype=jnp.float32, name="seg_embed")
        t = input_ids.shape[1]
        x = (tok(input_ids) + pos(jnp.arange(t)[None, :]) + seg(segment_ids))
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_embed")(x)
        x = nn.Dropout(cfg.dropout)(x, deterministic=deterministic)
        for i in range(cfg.layers):
            x = EncoderLayer(cfg, self.mesh, name=f"layer_{i}")(
                x, pad_mask, deterministic)
        if cfg.tp_overlap and self.mesh is not None:
            # leave the Megatron-SP layout before the tied decode below
            # reads the vocab-sharded embedding TABLE
            x = comms.tp_activation_gathered(x, self.mesh)
        # MLM head: dense+gelu+LN then tied decode (embedding^T) + bias.
        h = nn.Dense(cfg.hidden, dtype=cfg.dtype, param_dtype=jnp.float32,
                     name="mlm_dense")(x)
        h = nn.gelu(h, approximate=True)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_mlm")(h)
        if return_hidden:
            # the vocab-chunked loss decodes against the tied embedding
            # itself (init always runs return_hidden=False, so mlm_bias
            # exists in the param tree)
            return h
        embedding = tok.variables["params"]["embedding"]
        logits = jnp.einsum("bth,vh->btv", h.astype(jnp.float32),
                            embedding.astype(jnp.float32))
        logits = logits + self.param(
            "mlm_bias", nn.initializers.zeros, (cfg.vocab_size,), jnp.float32)
        return logits


def make_init(cfg: BertConfig, mesh: Optional[Mesh] = None, seq_len: int = 128):
    if seq_len > cfg.max_positions:
        raise ValueError(
            f"seq_len={seq_len} exceeds max_positions={cfg.max_positions} "
            "(XLA would silently clamp position-embedding lookups)")
    model = BertMLM(cfg, mesh)
    # init traces through the model (incl. the SP shard_map, whose batch must
    # divide the data axis), so the dummy batch matches the mesh data size.
    b = 1
    if mesh is not None:
        b = mesh.shape.get("data", 1)

    def init_fn(rng):
        ids = jnp.zeros((b, seq_len), jnp.int32)
        return model.init(rng, ids, ids, jnp.ones((b, seq_len), bool),
                          deterministic=True)

    return model, init_fn


def _gather_masked(h: jax.Array, labels: jax.Array, budget: int,
                   rng: Optional[jax.Array]):
    """Keep only (up to ``budget``) masked positions per row.

    MLM labels ~15% of positions; decoding ALL of them against the 30k
    vocab wastes ~7x the head FLOPs+memory — the original BERT recipe
    scores a fixed ``max_predictions_per_seq`` gather instead, which is
    exactly this. Rows with more masked positions than the budget drop
    the overflow UNIFORMLY AT RANDOM (a positional stable-sort would
    systematically starve late-sequence tokens of gradient — the
    reference caps over a randomly-ordered candidate list): valid
    positions sort by a random key in [0,1), invalid by key+1, so valid
    always precede invalid and ties break randomly. Slack slots keep
    their -100 labels (gathered from invalid positions). Exact equality
    with the full path whenever the budget covers every row's masked
    count (the CE mean is order-invariant).

    Without an ``rng`` (eval), overflow drops the LAST masked positions
    instead: keys are the position indices, so the first ``budget`` valid
    positions are kept deterministically — a fixed random key would score
    the same arbitrary subset every eval, which is the same bias with
    less transparency.
    """
    valid = labels != -100
    if rng is None:
        key = jax.lax.broadcasted_iota(jnp.float32, labels.shape, 1)
    else:
        key = jax.random.uniform(rng, labels.shape)
    span = labels.shape[1] + 1.0
    idx = jnp.argsort(jnp.where(valid, key, span + key), axis=1)[:, :budget]
    h_g = jnp.take_along_axis(h, idx[..., None], axis=1)
    return h_g, jnp.take_along_axis(labels, idx, axis=1)


def _mlm_ce(model: BertMLM, params, out, labels, loss_chunk: int,
            mlm_gather: int, rng: Optional[jax.Array] = None):
    """CE over masked positions, full-logits or vocab-chunked against the
    TIED embedding (transposed) + mlm_bias — one definition for loss+eval.
    ``mlm_gather > 0`` scores only that many gathered masked positions
    per row (:func:`_gather_masked`); requires the hidden-states path."""
    from dtf_tpu.ops.losses import chunked_lm_cross_entropy

    if mlm_gather:
        out, labels = _gather_masked(out, labels, mlm_gather, rng)
        if not loss_chunk:
            # gathered rows still need the tied decode; one vocab-wide
            # "chunk" reuses the single decode implementation
            loss_chunk = model.cfg.vocab_size
    if loss_chunk:
        return chunked_lm_cross_entropy(
            out, params["token_embed"]["embedding"].T, labels,
            chunk=loss_chunk, bias=params["mlm_bias"], ignore_index=-100)
    return softmax_cross_entropy(out, labels, ignore_index=-100)


def make_eval(model: BertMLM, *, loss_chunk: int = 0, mlm_gather: int = 0):
    """Held-out MLM eval: mean CE over masked positions + perplexity.
    ``loss_chunk``/``mlm_gather``: see :func:`make_loss` — eval must fit
    wherever training does. With ``mlm_gather``, rows whose masked count
    exceeds the budget are subsampled: eval scores the FIRST ``budget``
    masked positions of each row (deterministic; see
    :func:`_gather_masked`), so size the budget to
    ``max_predictions_per_seq`` for exact full-coverage eval."""

    def eval_fn(params, extra, batch):
        out = model.apply(
            {"params": params}, batch["input_ids"], batch["segment_ids"],
            batch["attention_mask"].astype(bool), deterministic=True,
            return_hidden=loss_chunk > 0 or mlm_gather > 0)
        loss, _ = _mlm_ce(model, params, out, batch["mlm_labels"],
                          loss_chunk, mlm_gather)
        return {"eval_mlm_loss": loss, "eval_mlm_ppl": jnp.exp(loss)}

    return eval_fn


def make_loss(model: BertMLM, *, loss_chunk: int = 0, mlm_gather: int = 0):
    """MLM loss: CE over masked positions (labels==-100 elsewhere).

    ``loss_chunk > 0``: vocab-chunked fused CE against the tied embedding
    (see :func:`dtf_tpu.ops.losses.chunked_lm_cross_entropy`) — removes
    the O(batch·seq·vocab) logits memory. ``mlm_gather > 0``: score only
    that many gathered masked positions per row (the original BERT
    ``max_predictions_per_seq`` recipe — ~7x less head work at a 15%
    mask rate; see :func:`_gather_masked`). Both compose. Neither is for
    TP runs (the embedding is vocab-sharded P('model', None) there)."""

    def loss_fn(params, extra, batch, rng):
        rng, r_gather = jax.random.split(rng)
        out = model.apply(
            {"params": params}, batch["input_ids"], batch["segment_ids"],
            batch["attention_mask"].astype(bool),
            deterministic=model.cfg.dropout == 0.0,
            rngs={"dropout": rng} if model.cfg.dropout else {},
            return_hidden=loss_chunk > 0 or mlm_gather > 0)
        loss, n = _mlm_ce(model, params, out, batch["mlm_labels"],
                          loss_chunk, mlm_gather, rng=r_gather)
        # weight=n: grad-accum combines microbatches by valid-position count,
        # matching the full-batch per-position mean exactly.
        return loss, LossAux(extra=extra, metrics={"mlm_positions": n},
                             weight=n)

    return loss_fn
