"""GPT over pipeline parallelism — real transformer blocks as pipe stages.

VERDICT r2 weak #4: the pipeline schedules (`dtf_tpu.parallel.pipeline`)
had only ever run tanh-MLP toy stages. This module puts the flagship model
through them: the embedding and LM head run outside the pipeline under
plain GSPMD, and the `cfg.layers` transformer blocks are split into
homogeneous stages stacked along a leading row dim sharded ``P('pipe')``
(GPipe) or interleaved Megatron-style (``interleave_v > 1``).

Composition contract: inside the pipeline body we are already inside
``shard_map`` (manual over `pipe`, `data` — and `seq` under PP x SP), so
the blocks run with ``mesh=None``: dense/flash attention per shard, or —
when the mesh carries a non-trivial ``seq`` axis — the per-shard ring
(halo for windowed layers) via ``manual_seq``, using the enclosing manual
axes directly instead of a nested shard_map. dp x pp x sp is the
supported product here; Megatron TP composes either with the
non-pipelined path (`dtf_tpu.models.gpt.tp_rules`) or inside stages via
`gpt_pipe_tp` (without sp). MoE-in-pipe is rejected explicitly (`sow`
cannot cross the shard_map/scan boundary).

Reference citation: the reference has no PP at all (SURVEY.md §2c marks it
out of scope); this exists because a complete TPU framework needs layer
scaling beyond one chip's HBM.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh, PartitionSpec as P

from dtf_tpu.core.train import LossAux
from dtf_tpu.models.gpt import Block, GPTConfig
from dtf_tpu.ops.losses import softmax_cross_entropy
from dtf_tpu.parallel import pipeline as pp

PyTree = Any


class GPTEmbed(nn.Module):
    """Token embedding (+dropout) — runs OUTSIDE the pipeline."""

    cfg: GPTConfig

    @nn.compact
    def __call__(self, input_ids, *, deterministic: bool = True):
        cfg = self.cfg
        x = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="token_embed")(input_ids)
        return nn.Dropout(cfg.dropout)(x, deterministic=deterministic)


class GPTHead(nn.Module):
    """Final LN + untied LM head — runs OUTSIDE the pipeline."""

    cfg: GPTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        return nn.Dense(cfg.vocab_size, use_bias=False, dtype=jnp.float32,
                        param_dtype=jnp.float32, name="lm_head")(x)


class GPTStage(nn.Module):
    """``n_layers`` consecutive transformer blocks — one pipeline stage.

    Activation-shape-preserving ([mb, T, d] → [mb, T, d]), the homogeneity
    the stacked-stage schedules require. Blocks run mesh-less (see module
    docstring); remat applies per block when ``cfg.remat``.

    Per-layer windows (``attn_global_every``) are supported when the
    local/global pattern's period divides ``n_layers``: every stage then
    holds the SAME [window, ..., global] layer sequence, so the stacked
    schedule's homogeneity is preserved (validate_pipe_cfg enforces the
    divisibility). ``cfg.layer_window(i)`` is stage-offset-invariant in
    that case because the pattern repeats with the period.
    """

    cfg: GPTConfig
    n_layers: int
    #: PP x SP: stage activations arrive seq-sharded inside the pipeline's
    #: shard_map; attention then uses per-shard ring/halo collectives (see
    #: CausalSelfAttention.manual_seq). Init must use manual_seq=False
    #: (no axis context outside shard_map) — the params are identical.
    manual_seq: bool = False

    @nn.compact
    def __call__(self, x):
        block = Block
        if self.cfg.remat:
            block = nn.remat(Block, static_argnums=(2,))
        for i in range(self.n_layers):
            x = block(self.cfg, None, False, self.cfg.layer_window(i),
                      manual_seq=self.manual_seq,
                      name=f"block_{i}")(x, True)
        return x


def validate_pipe_cfg(cfg: GPTConfig, n_stages: int, interleave_v: int = 1,
                      seq_shards: int = 1):
    rows = n_stages * interleave_v
    if cfg.layers % rows:
        raise ValueError(
            f"layers={cfg.layers} must divide into {n_stages} stages x "
            f"{interleave_v} chunks = {rows} rows")
    if cfg.attn_global_every and (cfg.layers // rows) % cfg.attn_global_every:
        raise ValueError(
            f"attn_global_every={cfg.attn_global_every} must divide the "
            f"per-stage layer count ({cfg.layers // rows}) so every stage "
            "holds the same local/global layer pattern (the stacked-stage "
            "schedule requires homogeneous stages); adjust layers/stages "
            "or the period")
    if cfg.moe_every:
        raise ValueError(
            "MoE blocks cannot run inside the pipeline (sow crosses the "
            "shard_map/scan boundary); use the non-pipelined path for MoE")
    if cfg.decode_len:
        raise ValueError("decode mode is not pipelined")
    if cfg.dropout:
        raise ValueError(
            "dropout>0 is not supported in the pipelined path (stages run "
            "deterministic inside the schedule); the non-pipelined path "
            "honors it — silently dropping regularization is worse than "
            "refusing")
    if cfg.attn_impl == "zigzag":
        raise ValueError(
            "attn_impl='zigzag' is not supported with mesh_pipe (the "
            "permuted data layout would have to thread through the "
            "microbatch schedule); PP x SP uses the plain ring")
    if seq_shards > 1:
        if cfg.attn_impl not in ("auto", "ring"):
            raise ValueError(
                f"attn_impl={cfg.attn_impl!r} cannot run seq-sharded "
                "inside pipeline stages; PP x SP routes auto/ring to "
                "per-shard ring (halo when windowed)")
    elif cfg.attn_impl == "ring":
        raise ValueError(
            "attn_impl='ring' needs the seq mesh axis, but pipeline "
            "stages run mesh-less without it; use dense/flash with "
            "mesh_pipe alone, or add mesh_seq (PP x SP)")
    return cfg.layers // rows


def make_pipe_init(cfg: GPTConfig, mesh: Mesh, *, seq_len: int = 128,
                   interleave_v: int = 1, axis_name: str = "pipe"):
    """Init fn for the pipelined GPT's params:
    ``{"embed": ..., "stages": [rows, ...] stacked, "head": ...}``.

    The stage stack is initialized per-row (vmap over split rngs) and, for
    the interleaved schedule, laid out device-major via
    :func:`dtf_tpu.parallel.pipeline.reorder_stages`.
    """
    n_stages = mesh.shape.get(axis_name, 1)
    per_row = validate_pipe_cfg(cfg, n_stages, interleave_v,
                                mesh.shape.get("seq", 1))
    rows = n_stages * interleave_v
    stage = GPTStage(cfg, per_row)   # init runs OUTSIDE shard_map: no
    b = mesh.shape.get("data", 1)    # manual_seq (params are identical)

    def init_fn(rng):
        r_e, r_s, r_h = jax.random.split(rng, 3)
        ids = jnp.zeros((b, seq_len), jnp.int32)
        x = jnp.zeros((1, seq_len, cfg.d_model), cfg.dtype)
        embed = GPTEmbed(cfg).init(r_e, ids)["params"]
        stacked = pp.init_stacked(
            lambda r: stage.init(r, x)["params"], rows, r_s)
        if interleave_v > 1:
            stacked = pp.reorder_stages(stacked, n_stages, interleave_v)
        head = GPTHead(cfg).init(r_h, x)["params"]
        return {"params": {"embed": embed, "stages": stacked, "head": head}}

    return init_fn


def pipe_rules(axis_name: str = "pipe"):
    """Param-placement rules: every stage row rides the pipe axis; embed and
    head stay replicated (shard them over data via ZeRO-1 as usual)."""
    return [(r"^stages/", P(axis_name))]


def make_pipe_loss(cfg: GPTConfig, mesh: Mesh, *, n_microbatches: int,
                   interleave_v: int = 1, axis_name: str = "pipe"):
    """Loss fn (make_train_step-compatible) running blocks through the
    GPipe schedule (or the interleaved one when ``interleave_v > 1``).

    PP x SP: when the mesh has a non-trivial ``seq`` axis, microbatch
    activations ride the schedule seq-sharded (batch_spec gains 'seq')
    and the stages run ring/halo attention per shard
    (:class:`GPTStage` ``manual_seq``)."""
    n_stages = mesh.shape.get(axis_name, 1)
    seq_shards = mesh.shape.get("seq", 1)
    per_row = validate_pipe_cfg(cfg, n_stages, interleave_v, seq_shards)
    sp = seq_shards > 1
    stage = GPTStage(cfg, per_row, manual_seq=sp)
    batch_spec = P("data", "seq") if sp else P("data")

    def stage_fn(stage_params, x):
        return stage.apply({"params": stage_params}, x)

    if interleave_v > 1:
        pipe = pp.pipeline_interleaved(stage_fn, n_microbatches, mesh,
                                       interleave_v, axis_name=axis_name,
                                       batch_spec=batch_spec,
                                       check_vma=not sp)
    else:
        pipe = pp.pipeline_spmd(stage_fn, n_microbatches, mesh,
                                axis_name=axis_name,
                                batch_spec=batch_spec,
                                check_vma=not sp)

    def loss_fn(params, extra, batch, rng):
        del rng  # blocks run deterministic inside the schedule
        p = params["params"] if "params" in params else params
        x = GPTEmbed(cfg).apply({"params": p["embed"]}, batch["input_ids"])
        x = pipe(p["stages"], x)
        logits = GPTHead(cfg).apply({"params": p["head"]}, x)
        loss, n = softmax_cross_entropy(logits, batch["labels"],
                                        ignore_index=-100)
        return loss, LossAux(extra=extra, metrics={"lm_tokens": n}, weight=n)

    return loss_fn


def make_pipe_grads_1f1b(cfg: GPTConfig, mesh: Mesh, *, n_microbatches: int,
                         axis_name: str = "pipe"):
    """Grads fn (make_train_step_from_grads-compatible) running the blocks
    through the fused-1F1B schedule
    (:func:`dtf_tpu.parallel.pipeline.pipeline_1f1b_grads`).

    Same param layout, state sharding, and numerics as
    :func:`make_pipe_loss` + ``jax.grad`` (loss = token-mean cross-entropy,
    gradient = d(mean)/dθ), but with an O(S) activation stash instead of
    O(M): embedding runs inside stage 0's forward rounds, head +
    cross-entropy (value and vjp) inside the last stage's backward rounds,
    and each stage's backward recomputes its forward from the stashed stage
    input. PP x SP composes exactly as in :func:`make_pipe_loss`
    (seq-sharded microbatches, per-shard ring/halo attention); interleaved
    chunks (``interleave_v``) are a GPipe-path-only feature.

    One edge-case delta vs the un-pipelined loss: the all-ignored-tokens
    clamp (``losses._masked_mean``) applies per micro-shard here rather
    than once globally, so the two differ only when an entire microbatch
    shard has zero valid label positions (it then contributes weight 1
    with loss-sum 0 instead of nothing) — unreachable in CLM training,
    where every position carries a label.
    """
    return _make_pipe_grads(cfg, mesh, n_microbatches=n_microbatches,
                            axis_name=axis_name, schedule="1f1b")


def make_pipe_grads_zb(cfg: GPTConfig, mesh: Mesh, *, n_microbatches: int,
                       axis_name: str = "pipe"):
    """Zero-bubble variant of :func:`make_pipe_grads_1f1b`.

    Identical contract and param layout, but the blocks run through
    :func:`dtf_tpu.parallel.pipeline.pipeline_zb_grads` — each stage's
    backward split into B (activation grad, critical path) and W (weight
    grad, deferred into the 1F1B drain bubble). Grads are bitwise equal to
    the 1F1B schedule on integer data and allclose on real data; the
    schedule-level win is priced by
    :func:`dtf_tpu.parallel.pipeline.schedule_bubble_model`.
    """
    return _make_pipe_grads(cfg, mesh, n_microbatches=n_microbatches,
                            axis_name=axis_name, schedule="zb")


def _make_pipe_grads(cfg: GPTConfig, mesh: Mesh, *, n_microbatches: int,
                     axis_name: str, schedule: str):
    n_stages = mesh.shape.get(axis_name, 1)
    seq_shards = mesh.shape.get("seq", 1)
    per_row = validate_pipe_cfg(cfg, n_stages, 1, seq_shards)
    sp = seq_shards > 1
    stage = GPTStage(cfg, per_row, manual_seq=sp)
    batch_spec = P("data", "seq") if sp else P("data")
    schedule_fn = {"1f1b": pp.pipeline_1f1b_grads,
                   "zb": pp.pipeline_zb_grads}[schedule]

    def first_fn(p_embed, mb):
        return GPTEmbed(cfg).apply({"params": p_embed}, mb["input_ids"])

    def stage_fn(p, x):
        return stage.apply({"params": p}, x)

    def last_fn(p_head, y, mb):
        logits = GPTHead(cfg).apply({"params": p_head}, y)
        loss, n = softmax_cross_entropy(logits, mb["labels"],
                                        ignore_index=-100)
        n = n.astype(jnp.float32)
        # per-(micro)shard SUM + weight; Σ over microbatches and batch
        # shards reproduces the full-batch token mean exactly.
        return loss * n, n

    run = schedule_fn(
        first_fn, stage_fn, last_fn, n_microbatches, mesh,
        axis_name=axis_name, batch_spec=batch_spec, check_vma=False)

    def grads_fn(params, extra, batch, rng):
        del rng  # blocks run deterministic inside the schedule
        wrapped = isinstance(params, dict) and "params" in params
        p = params["params"] if wrapped else params
        ls, ws, (gf, gs, gl) = run(p["embed"], p["stages"], p["head"], batch)
        scale = lambda g, ref: jax.tree.map(
            lambda t, u: (t / ws).astype(u.dtype), g, ref)
        g = {"embed": scale(gf, p["embed"]),
             "stages": scale(gs, p["stages"]),
             "head": scale(gl, p["head"])}
        grads = {"params": g} if wrapped else g
        return ls / ws, LossAux(extra=extra, metrics={"lm_tokens": ws},
                                weight=ws), grads

    return grads_fn


def make_pipe_eval(cfg: GPTConfig, n_stages: int, *, interleave_v: int = 1,
                   seq_shards: int = 1):
    """Held-out eval for the pipelined param layout (VERDICT r3 #7).

    The eval step runs UN-pipelined: stage rows applied sequentially in
    logical order against the SAME stacked params the pipeline trains (the
    math :func:`make_sequential_loss` already proves equal). Eval is off
    the training critical path, so letting GSPMD move each P('pipe') row to
    wherever the eval computation runs is the right trade — no schedule, no
    microbatching, just perplexity. ``seq_shards`` only loosens validation
    for PP x SP configs (explicit attn_impl='ring'); the eval stages
    themselves run mesh-less full-T attention (ring falls back to dense
    without a mesh).
    """
    per_row = validate_pipe_cfg(cfg, n_stages, interleave_v, seq_shards)
    stage = GPTStage(cfg, per_row)
    order = pp.interleaved_stage_order(n_stages, interleave_v)
    inv = [order.index(s) for s in range(n_stages * interleave_v)]

    def eval_fn(params, extra, batch):
        del extra
        p = params["params"] if "params" in params else params
        x = GPTEmbed(cfg).apply({"params": p["embed"]}, batch["input_ids"])
        for s in inv:
            row = jax.tree.map(lambda t: t[s], p["stages"])
            x = stage.apply({"params": row}, x)
        logits = GPTHead(cfg).apply({"params": p["head"]}, x)
        loss, _ = softmax_cross_entropy(logits, batch["labels"],
                                        ignore_index=-100)
        return {"eval_loss": loss, "eval_ppl": jnp.exp(loss)}

    return eval_fn


def make_sequential_loss(cfg: GPTConfig, n_stages: int, *,
                         interleave_v: int = 1, seq_shards: int = 1):
    """The unpipelined reference: identical math on the SAME stacked params
    (stage rows applied in logical order) — the parity oracle for tests.
    ``seq_shards`` only loosens validation for PP x SP configs (see
    :func:`make_pipe_eval`)."""
    per_row = validate_pipe_cfg(cfg, n_stages, interleave_v, seq_shards)
    stage = GPTStage(cfg, per_row)
    order = pp.interleaved_stage_order(n_stages, interleave_v)
    # invert: logical stage s lives at stack row order.index(s)
    inv = [order.index(s) for s in range(n_stages * interleave_v)]

    def loss_fn(params, extra, batch, rng):
        del rng
        p = params["params"] if "params" in params else params
        x = GPTEmbed(cfg).apply({"params": p["embed"]}, batch["input_ids"])
        for s in inv:
            row = jax.tree.map(lambda t: t[s], p["stages"])
            x = stage.apply({"params": row}, x)
        logits = GPTHead(cfg).apply({"params": p["head"]}, x)
        loss, n = softmax_cross_entropy(logits, batch["labels"],
                                        ignore_index=-100)
        return loss, LossAux(extra=extra, metrics={"lm_tokens": n}, weight=n)

    return loss_fn
