"""Model zoo: one model per BASELINE workload config.

- mnist: softmax regression (the reference's actual model) + MLP
- resnet: ResNet-20 (CIFAR) / ResNet-50 (ImageNet)
- bert: BERT-base encoder MLM pretraining
- widedeep: Wide&Deep recsys with row-sharded embedding tables
"""
