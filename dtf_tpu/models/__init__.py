"""Model zoo: one model per BASELINE workload config.

- mnist: softmax regression (the reference's actual model) + MLP
- resnet: ResNet-20 (CIFAR) / ResNet-50 (ImageNet)
- bert: BERT-base encoder MLM pretraining
- widedeep: Wide&Deep recsys with row-sharded embedding tables
"""


def rulebooks():
    """name → param-placement rulebook, one entry per model that ships one.

    The registration point the static analyzer builds on
    (``dtf_tpu.analysis.configs`` wires each rulebook to its mesh/step
    construction): a new model's rules added here are one registry entry
    away from full rule-lint + comms-budget coverage. Imports stay lazy —
    this package must be importable without pulling every model.
    """
    from dtf_tpu.models import bert, gpt, gpt_pipe, gpt_pipe_tp, widedeep

    return {
        "mnist": (),                       # pure DP: ZeRO-1 shards opt state
        "resnet": (),                      # pure DP
        "bert": tuple(bert.tp_rules),
        "widedeep": tuple(widedeep.rules),
        "gpt": tuple(gpt.tp_rules),
        "gpt_pipe": tuple(gpt_pipe.pipe_rules()),
        "gpt_pipe_tp": tuple(gpt_pipe_tp.pipe_tp_rules()),
    }
