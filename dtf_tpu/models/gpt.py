"""GPT-style causal decoder LM — the long-context flagship.

Beyond the reference's capability list (SURVEY.md §5.7: nothing in
`zjj2wry/distributed-tensorflow` scales sequence length), but first-class
here: this model is the consumer that ties the framework's long-context and
parallelism machinery together —

- **flash attention** (:mod:`dtf_tpu.ops.flash_attention`): fused Pallas
  kernel for the single/tensor-parallel path, wrapped in ``shard_map`` over
  (data, model) so batch/head shards each run a local kernel;
- **ring attention** (:mod:`dtf_tpu.ops.attention`): context parallelism
  over the ``seq`` axis for sequences that don't fit one chip;
- **Megatron TP** over ``model`` (:data:`tp_rules`), same scheme as BERT;
- optional **Switch-MoE** FFN layers (:mod:`dtf_tpu.parallel.moe`) for
  expert parallelism over ``expert``;
- **remat** (``jax.checkpoint``) per block — the HBM-for-FLOPs trade that
  long sequences need.

Pre-LN blocks, RoPE positions (global positions, so they are correct under
sequence sharding), optional grouped-query attention (``kv_heads`` — the
KV cache shrinks by heads/kv_heads, the decode-memory lever), untied LM
head, bf16 compute / f32 params.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh, PartitionSpec as P

from dtf_tpu.core import comms
from dtf_tpu.core.train import LossAux
from dtf_tpu.ops import attention as att
from dtf_tpu.ops import flash_attention as fa
from dtf_tpu.ops.losses import softmax_cross_entropy
from dtf_tpu.parallel import moe as moe_lib


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    #: GPT-2's 50257 BPE vocab padded to a multiple of 128 (the Megatron /
    #: nanoGPT convention): the embedding rows and lm_head columns shard
    #: evenly over any power-of-two `model` axis AND tile the TPU lane
    #: width; 50257 would leave every TP shard ragged (caught by
    #: `python -m dtf_tpu.analysis` as indivisible-dim). The 47 pad tokens
    #: never appear in data; their logits just ride the softmax.
    vocab_size: int = 50304
    d_model: int = 768
    layers: int = 12
    heads: int = 12
    d_ff: int = 3072
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.bfloat16
    rope_theta: float = 10000.0
    #: grouped-query attention: number of shared K/V heads (None = heads,
    #: i.e. plain MHA). Must divide ``heads``. The KV cache shrinks by
    #: heads/kv_heads — the decode-memory lever (cache is the decode
    #: footprint at long ``decode_len``).
    kv_heads: Optional[int] = None
    #: attention backend: auto (ring if seq-sharded, flash on tpu, else
    #: dense), or force one of dense|flash|ring.
    attn_impl: str = "auto"
    #: sliding-window attention: query t sees keys in (t-window, t].
    #: 0 = full causal. O(T·window) compute on the flash path (out-of-window
    #: blocks are grid-skipped). Under seq sharding, ring/auto routes to
    #: halo attention (one neighbor-tail ppermute, no ring rotation);
    #: zigzag rejects windows (its permuted layout breaks locality).
    attn_window: int = 0
    #: with attn_window > 0: every k-th layer (1-indexed) uses FULL causal
    #: attention instead — the alternating local/global pattern that keeps
    #: long-range paths while most layers pay O(T·window). 0 = all layers
    #: windowed. Each decode layer sizes its own cache (window slots for
    #: local layers, decode_len for global ones).
    attn_global_every: int = 0
    #: flash-kernel head fold: batch this many heads per forward grid
    #: step (must divide heads; 1 = the proven 2-D kernel). Perf knob for
    #: the flash path only — see ops/flash_attention.py.
    flash_block_h: int = 1
    #: every k-th block uses a Switch-MoE FFN (0 = all dense).
    moe_every: int = 0
    moe: moe_lib.MoeConfig = moe_lib.MoeConfig()
    #: jax.checkpoint each block (long-context memory trade).
    remat: bool = False
    #: >0 enables single-token decode mode with a KV cache of this length
    #: (the "cache" collection; see :func:`generate`).
    decode_len: int = 0
    #: "" = store K/V at ``dtype`` (bf16); "int8" = symmetric per-slot
    #: per-head quantization (amax over d_head -> one f32 scale per
    #: [b, kv_head, slot]): the cache holds HALF the bytes — the third
    #: serving memory lever, multiplicative with GQA (heads/kv_heads) and
    #: the rolling window (decode_len/window). Dequantized at read; the
    #: scale adds 1/d_head overhead (~0.8% at d_head=64).
    kv_cache_dtype: str = ""
    #: multi-token applies may CONTINUE an advanced cache: rope positions
    #: and cache slots offset by cache_index and attention runs against the
    #: full cache, so a long prompt can prefill in bounded-memory chunks
    #: (``generate(..., prefill_chunk=...)``). Static flag — the default
    #: one-shot prefill keeps its flash-kernel fast path.
    chunked_prefill: bool = False
    #: continuous-batching decode mode (:mod:`dtf_tpu.serve`): the
    #: ``cache_index`` variable is PER-ROW ([B] int32, one independent
    #: position per batch slot) instead of one scalar shared by the whole
    #: batch, so each slot of a serving batch can sit at a different
    #: sequence position — a slot resets to index 0 when a new request is
    #: admitted while its neighbors keep decoding. Single-token steps only
    #: (prefill goes through a sliced batch-1 ``chunked_prefill`` model —
    #: see ``serve/engine.py``); a stale slot's old contents need no
    #: clearing because slot validity is derived from the index
    #: (``p_s >= 0`` masks every slot the new request hasn't written).
    slot_decode: bool = False
    #: latency-hiding collective matmul for the Megatron TP projections
    #: (q/k/v + attn_out, mlp_in/mlp_out): the blocking all-gather /
    #: reduce-scatter GSPMD schedules around each sharded einsum becomes a
    #: ppermute ring overlapped with per-chunk matmuls
    #: (:mod:`dtf_tpu.ops.collective_matmul`; docs/OVERLAP.md). Exact
    #: numerics parity with the GSPMD path; no-op unless the mesh has a
    #: real 'model' axis and shapes divide (comms.tp_overlap_viable).
    tp_overlap: bool = False
    #: low-precision compute tier for the TP projections (docs/TUNING.md):
    #: "" = bf16 status quo (no tuner consult), "auto" = the banked
    #: kernel-tune winner per projection site, "int8"/"fp8" = explicit pin
    #: (wins with one WARN over a measured winner). Forward-only: the
    #: custom_vjp keeps gradients full-precision against bf16 master
    #: weights, and on the tp_overlap rings the COMMUNICATED operand is
    #: what quantizes (~2x fewer ring bytes). The serving draft engine is
    #: the first consumer (serve_gpt --draft_precision): the bf16
    #: verifier keeps emitted tokens byte-identical regardless.
    matmul_precision: str = ""

    def __post_init__(self):
        if self.kv_heads is not None and (
                self.kv_heads < 1 or self.heads % self.kv_heads):
            raise ValueError(
                f"kv_heads={self.kv_heads} must be >=1 and divide "
                f"heads={self.heads}")
        if self.attn_window < 0:
            # a negative window silently masks EVERY key: all-zero outputs
            # on the dense path, all--inf softmax (NaN) in decode
            raise ValueError(f"attn_window={self.attn_window} must be >= 0")
        if self.attn_global_every < 0:
            raise ValueError(
                f"attn_global_every={self.attn_global_every} must be >= 0")
        if self.kv_cache_dtype not in ("", "int8"):
            raise ValueError(
                f"kv_cache_dtype={self.kv_cache_dtype!r} must be '' (store "
                "at dtype) or 'int8'")
        if self.matmul_precision not in ("", "auto", "bf16", "int8",
                                         "fp8"):
            raise ValueError(
                f"matmul_precision={self.matmul_precision!r} must be '' "
                "(bf16, no tuner), 'auto' (kernel-tune winner), 'bf16', "
                "'int8' or 'fp8'")
        if self.slot_decode and self.decode_len <= 0:
            raise ValueError(
                "slot_decode requires decode_len > 0 (it is a property of "
                "the KV-cache decode mode)")
        if self.slot_decode and self.chunked_prefill:
            raise ValueError(
                "slot_decode and chunked_prefill are different models of "
                "the same cache: the serving engine slices one slot into a "
                "batch-1 chunked_prefill model instead (serve/engine.py)")

    def layer_window(self, layer: int) -> int:
        """Effective sliding window for layer ``layer`` (0-indexed): 0 when
        the layer is a designated global layer, else ``attn_window``."""
        if (self.attn_window and self.attn_global_every
                and (layer + 1) % self.attn_global_every == 0):
            return 0
        return self.attn_window

    @property
    def kv_heads_resolved(self) -> int:
        return self.heads if self.kv_heads is None else self.kv_heads

    @staticmethod
    def by_name(name: str) -> "GPTConfig":
        """The ONE size registry — every CLI/bench size switch routes
        here so adding a size is a single edit. Raises KeyError with the
        valid names for a typo (callers convert to their UsageError)."""
        sizes = {"small": GPTConfig.gpt2_small,
                 "medium": GPTConfig.gpt2_medium,
                 "draft": GPTConfig.gpt2_draft,
                 "tiny": GPTConfig.tiny}
        if name not in sizes:
            raise KeyError(
                f"unknown GPT size {name!r}; pick one of {sorted(sizes)}")
        return sizes[name]()

    @staticmethod
    def gpt2_small() -> "GPTConfig":
        return GPTConfig()

    @staticmethod
    def gpt2_medium() -> "GPTConfig":
        """GPT-2 medium (355M): the single-chip MFU sweet spot — wider
        matmuls (d_model 1024, d_ff 4096) fill the MXU better than
        small's 768/3072 while params+adam+ZeRO-1 still fit one v5e."""
        return GPTConfig(d_model=1024, layers=24, heads=16, d_ff=4096)

    @staticmethod
    def gpt2_draft() -> "GPTConfig":
        """The speculative-decoding DRAFT size (~25M non-embedding):
        shares the GPT-2 vocab (a draft must propose in the verifier's
        token space) at a quarter of small's depth and half its width —
        cheap enough that k proposals cost less than one verifier step,
        deep enough to track small's greedy stream on natural text."""
        return GPTConfig(d_model=384, layers=3, heads=6, d_ff=1536)

    @staticmethod
    def tiny(**kw) -> "GPTConfig":
        return GPTConfig(vocab_size=128, d_model=32, layers=2, heads=4,
                         d_ff=64, **kw)


def effective_attn_impl(impl: str, seq_sharded: bool) -> str:
    """Resolve ``attn_impl='auto'`` exactly as the attention block
    dispatches it (ring when seq-sharded, flash on TPU, dense otherwise).

    THE single source of truth for the dispatch: launchers call this to
    decide ``--grad_shard`` viability (everything but ``dense`` runs in a
    shard_map the per-shard-group vmap cannot nest — docs/ZERO.md), so a
    dispatch change here cannot drift from the blocker logic.
    """
    if impl != "auto":
        return impl
    if seq_sharded:
        return "ring"
    return "flash" if jax.default_backend() == "tpu" else "dense"


#: Megatron TP placement over the `model` mesh axis.
tp_rules = [
    (r"token_embed/embedding", P("model", None)),
    (r"(query|key|value)/kernel", P(None, "model")),
    (r"attn_out/kernel", P("model", None)),
    (r"mlp_in/kernel", P(None, "model")),
    (r"mlp_out/kernel", P("model", None)),
    (r"(query|key|value|mlp_in)/bias", P("model")),
    (r"lm_head/kernel", P(None, "model")),
] + moe_lib.ep_rules()


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding. x [B,H,T,D] (D even), positions [T] global indices —
    correct under seq sharding because positions are global, not local.
    ``positions`` may also be PER-ROW [B,T] (the ``slot_decode`` step, where
    every serving slot sits at its own position); the angles then broadcast
    over heads only."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    angles = positions[..., None].astype(jnp.float32) * freqs    # [...,T,D/2]
    if angles.ndim == 3:                   # [B,T,D/2] → broadcast over heads
        angles = angles[:, None]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., 0::2], x[..., 1::2]
    y1 = x1 * cos - x2 * sin
    y2 = x1 * sin + x2 * cos
    out = jnp.stack([y1, y2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


def _kv_quant(a: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 quantization over the last (d_head) axis: returns
    (int8 values, f32 scale with keepdims). Zero rows quantize to zeros
    with the epsilon scale — dequant reproduces zero exactly."""
    s = jnp.maximum(jnp.max(jnp.abs(a.astype(jnp.float32)), axis=-1,
                            keepdims=True), 1e-6) / 127.0
    q = jnp.clip(jnp.round(a.astype(jnp.float32) / s),
                 -127, 127).astype(jnp.int8)
    return q, s


def _cache_read(cfg, cvar, svar) -> jax.Array:
    """Cache contents at compute dtype (dequantizing when int8). XLA can
    fuse the dequant multiply into the consuming einsum; the capacity win
    (half the resident bytes) holds regardless."""
    if svar is None:
        return cvar.value
    return (cvar.value.astype(jnp.float32) * svar.value).astype(cfg.dtype)


def _cache_put_at(cfg, cvar, svar, slots, a) -> None:
    """Gather-indexed cache write (prefill paths) — ONE definition with
    :func:`_cache_put_dyn` of how quantization happens, so the three
    write sites cannot desynchronize."""
    if svar is None:
        cvar.value = cvar.value.at[:, :, slots, :].set(a.astype(cfg.dtype))
    else:
        q, s = _kv_quant(a)
        cvar.value = cvar.value.at[:, :, slots, :].set(q)
        svar.value = svar.value.at[:, :, slots, :].set(s)


def _cache_put_dyn(cfg, cvar, svar, slot, a) -> None:
    """Single-slot dynamic cache write (the decode step)."""
    if svar is None:
        cvar.value = jax.lax.dynamic_update_slice_in_dim(
            cvar.value, a.astype(cfg.dtype), slot, axis=2)
    else:
        q, s = _kv_quant(a)
        cvar.value = jax.lax.dynamic_update_slice_in_dim(
            cvar.value, q, slot, axis=2)
        svar.value = jax.lax.dynamic_update_slice_in_dim(
            svar.value, s, slot, axis=2)


def _cache_put_span(cfg, cvar, svar, positions, a, active, cache_len) -> None:
    """Per-row multi-position cache write (the slot VERIFY step): batch row
    b writes ``a[b, :, j, :]`` at its own absolute position
    ``positions[b, j]`` — slot = position, the full-cache layout this mode
    requires. Positions at or past the cache end, and every position of an
    inactive row, are pointed at the out-of-range sentinel and DROPPED
    (never wrapped): a wrapped write would clobber live early positions
    with speculative K/V that a rejected tail could not roll back."""
    b = positions.shape[0]
    rows = jnp.arange(b)[:, None]                              # [B, 1]
    drop = positions >= cache_len
    if active is not None:
        drop = drop | ~active[:, None]
    slots = jnp.where(drop, cache_len, positions)              # OOB = drop

    def put(var, upd):                                         # upd [B,H,t,D]
        var.value = var.value.at[rows, :, slots, :].set(
            upd.transpose(0, 2, 1, 3), mode="drop")

    if svar is None:
        put(cvar, a.astype(cfg.dtype))
    else:
        q, s = _kv_quant(a)
        put(cvar, q)
        put(svar, s)


def _cache_put_rows(cfg, cvar, svar, slots, a, active=None) -> None:
    """Per-row single-slot cache write (the ``slot_decode`` step): batch row
    b writes its own slot ``slots[b]`` — the vectorized counterpart of
    :func:`_cache_put_dyn` for per-slot cache indices. ``a`` is [B,H,1,D];
    the two advanced indices (rows, slots) land the [B,H,D] update.
    ``active`` [B] bool masks the write per row (inactive rows scatter
    their CURRENT slot contents back — a gather+scatter no-op — so a slot
    mid-prefill rides the fixed-shape decode step untouched)."""
    rows = jnp.arange(a.shape[0])

    def put(var, upd):
        if active is not None:
            cur = var.value[rows, :, slots, :]
            upd = jnp.where(active[:, None, None], upd, cur)
        var.value = var.value.at[rows, :, slots, :].set(upd)

    if svar is None:
        put(cvar, a[:, :, 0, :].astype(cfg.dtype))
    else:
        q, s = _kv_quant(a)
        put(cvar, q[:, :, 0, :])
        put(svar, s[:, :, 0, :])


class CausalSelfAttention(nn.Module):
    cfg: GPTConfig
    mesh: Optional[Mesh]
    #: effective sliding window for THIS layer (cfg.layer_window(i) — 0 on
    #: designated global layers). No default on purpose: a call site that
    #: forgets to thread it must fail loudly, not silently train
    #: full-causal under a windowed config.
    window: int
    #: True when this module already runs INSIDE a shard_map manual over
    #: the 'seq' axis (the PP x SP composition: pipeline stages carry
    #: seq-sharded activations). RoPE positions then come from the axis
    #: index and attention uses the per-shard ring/halo collectives
    #: directly — a nested shard_map would be illegal here.
    manual_seq: bool = False

    def _cache_vars(self, b: int, kv_heads: int, d_head: int):
        """The KV-cache collection — ONE definition shared by the
        single-token decode branch and the prefill write, so their layouts
        cannot desynchronize. Rolling buffer under a sliding window:
        position p lives in slot p % L with L = window, so the cache holds
        exactly the last `window` positions — decode memory is O(window),
        not O(decode_len) (the Mistral rolling-cache recipe). Without a
        window, L = decode_len and slots are positions (slot = idx).

        Standard flax decode idiom: init() only ALLOCATES the cache
        (has_variable is False on the init trace, so no slot is written
        and cache_index stays 0); mutation happens only on real apply()
        calls. Without this guard, init's dummy token would occupy slot 0
        and every later step would be off by one.
        """
        cfg = self.cfg
        is_initialized = self.has_variable("cache", "cached_key")
        # NOTE: a new cache variable must also be added to
        # _BATCH_LED_CACHE_KEYS / _NON_BATCH_CACHE_KEYS below (beam search
        # reorders batch-led leaves by key path and asserts completeness).
        cache_len = (min(cfg.decode_len, self.window)
                     if self.window else cfg.decode_len)
        quant = cfg.kv_cache_dtype == "int8"
        store = jnp.int8 if quant else cfg.dtype
        ck = self.variable("cache", "cached_key", jnp.zeros,
                           (b, kv_heads, cache_len, d_head), store)
        cv = self.variable("cache", "cached_value", jnp.zeros,
                           (b, kv_heads, cache_len, d_head), store)
        sk = sv = None
        if quant:
            sk = self.variable("cache", "key_scale", jnp.zeros,
                               (b, kv_heads, cache_len, 1), jnp.float32)
            sv = self.variable("cache", "value_scale", jnp.zeros,
                               (b, kv_heads, cache_len, 1), jnp.float32)
        # slot_decode: one independent position counter per batch row (the
        # continuous-batching mode); otherwise the classic shared scalar.
        ci = self.variable("cache", "cache_index",
                           lambda: jnp.zeros((b,) if cfg.slot_decode else (),
                                             jnp.int32))
        return ck, cv, sk, sv, ci, cache_len, is_initialized

    @nn.compact
    def __call__(self, x, deterministic: bool, prefill_len=None,
                 decode_active=None):
        cfg = self.cfg
        d_head = cfg.d_model // cfg.heads
        kv_heads = cfg.kv_heads_resolved
        group = cfg.heads // kv_heads
        t = x.shape[1]
        if cfg.slot_decode and t != 1 and self.window:
            raise ValueError(
                "the slot VERIFY step (slot_decode, multi-token apply) "
                "needs the full windowless cache layout; "
                f"attn_window={self.window} rolls the buffer, so a "
                "rejected speculative tail would clobber live positions "
                "it cannot roll back")
        if prefill_len is not None and not (
                cfg.decode_len > 0 and t != 1 and cfg.chunked_prefill):
            raise ValueError(
                "prefill_len only applies to the chunked-prefill path "
                "(decode_len > 0, chunked_prefill=True, multi-token chunk)")
        if decode_active is not None and not cfg.slot_decode:
            raise ValueError(
                "decode_active only applies to the slot_decode/verify "
                "steps (per-row cache indices)")
        # ONE projection constructor for every branch (train + decode):
        # comms.TpDense is a drop-in nn.Dense (identical param tree). With
        # --tp_overlap, q/k/v become collective ag_matmuls and attn_out a
        # collective matmul_rs; otherwise (and in every non-viable shape,
        # e.g. decode's t=1) its dispatch is the plain einsum. PP x SP
        # stages run inside a manual shard_map already, where a nested one
        # would be illegal — hence the manual_seq gate.
        overlap = (cfg.tp_overlap and self.mesh is not None
                   and not self.manual_seq)
        dense = lambda name, nh: comms.TpDense(  # noqa: E731
            nh * d_head, self.mesh, "column", overlap=overlap,
            dtype=cfg.dtype, precision=cfg.matmul_precision, name=name)
        out_dense = lambda: comms.TpDense(  # noqa: E731
            cfg.d_model, self.mesh, "row", overlap=overlap,
            dtype=cfg.dtype, precision=cfg.matmul_precision,
            name="attn_out")

        def split(v, nh):
            return v.reshape(v.shape[0], t, nh, d_head).transpose(0, 2, 1, 3)

        q = split(dense("query", cfg.heads)(x), cfg.heads)
        k = split(dense("key", kv_heads)(x), kv_heads)
        v = split(dense("value", kv_heads)(x), kv_heads)

        def expand_kv(a):
            # GQA: query head h reads shared K/V head h // group. jnp.repeat
            # on the head axis produces exactly that alignment, and keeps
            # head-sharded layouts consistent (shard s's q heads see shard
            # s's repeated kv heads).
            return jnp.repeat(a, group, axis=1) if group > 1 else a

        if cfg.slot_decode and t != 1:
            # SLOT VERIFY (speculative decoding, serve/engine.py): t tokens
            # per row — the pending token plus k draft proposals — scored
            # in ONE batched pass, each row at its OWN cache position.
            # Position j of a row computes the same formula j sequential
            # slot_decode steps would: all t K/V land in the cache first
            # (slot = position; the full-cache layout, enforced above),
            # every query reads the POST-write cache — like the t=1 branch
            # reads its own freshly written K (which also keeps int8
            # self-reads dequantized identically) — and query j's validity
            # mask is the t=1 formula evaluated at index idx+j. Logits
            # agree with sequential decode to matmul-shape rounding (the
            # chunked-prefill parity class — batching t rows reassociates
            # reductions); the TESTED contract is token-stream identity,
            # exactly like chunked vs one-shot prefill's decode
            # continuation. Writes past the cache end DROP (never wrap —
            # _cache_put_span): their queries' tokens sit past the slot
            # budget and are never delivered. The caller rolls cache_index
            # back to the accepted boundary afterwards (cache_rollback);
            # rejected-tail K/V needs no clearing — validity is derived
            # from the index.
            b = x.shape[0]
            ck, cv, sk, sv, ci, cache_len, is_initialized = self._cache_vars(
                b, kv_heads, d_head)
            idx = ci.value                                         # [B]
            qpos = idx[:, None] + jnp.arange(t)                    # [B, t]
            q = rope(q, qpos, cfg.rope_theta)
            k = rope(k, qpos, cfg.rope_theta)
            if is_initialized:
                _cache_put_span(cfg, ck, sk, qpos, k,
                                active=decode_active, cache_len=cache_len)
                _cache_put_span(cfg, cv, sv, qpos, v,
                                active=decode_active, cache_len=cache_len)
                ci.value = (idx + t if decode_active is None
                            else idx + t * decode_active.astype(jnp.int32))
            slots = jnp.arange(cache_len)
            # query j sees slot s iff the t=1 step at index idx+j would:
            # p_s = newest position <= idx+j congruent to s, valid iff >= 0
            p_s = qpos[:, :, None] - jnp.remainder(
                qpos[:, :, None] - slots[None, None, :], cache_len)
            bias = jnp.where(p_s >= 0, 0.0, -jnp.inf)          # [B, t, L]
            keys = _cache_read(cfg, ck, sk)
            vals = _cache_read(cfg, cv, sv)
            qg = q.reshape(b, kv_heads, group, t, d_head)
            s = jnp.einsum("bkgtd,bkld->bkgtl", qg, keys,
                           preferred_element_type=jnp.float32)
            s = s * d_head ** -0.5 + bias[:, None, None]
            p = jax.nn.softmax(s, axis=-1)
            out = jnp.einsum("bkgtl,bkld->bkgtd", p.astype(vals.dtype),
                             vals, preferred_element_type=jnp.float32)
            out = out.astype(cfg.dtype).transpose(0, 3, 1, 2, 4)
            out = out.reshape(b, t, cfg.d_model)
            return out_dense()(out)

        if cfg.decode_len > 0 and t != 1 and cfg.chunked_prefill:
            # CHUNKED PREFILL: continue a (possibly already-advanced) cache
            # with a t-token chunk. Rope positions and cache slots offset by
            # cache_index, and attention runs against the FULL cache — chunk
            # i attends its own chunk's keys plus every pre-chunk position
            # still in its window, so consecutive chunk applies reproduce
            # the one-shot prefill exactly (parity-tested on logits; with
            # an int8 cache, pre-chunk keys read back dequantized, so
            # "exactly" relaxes to quantization tolerance). Costs
            # [t, L+t] dense scores per layer instead of the flash kernel:
            # the bounded-memory trade chunking exists for.
            b = x.shape[0]
            ck, cv, sk, sv, ci, cache_len, is_initialized = self._cache_vars(
                b, kv_heads, d_head)
            start = ci.value if is_initialized else jnp.int32(0)
            qpos = start + jnp.arange(t)
            q = rope(q, qpos, cfg.rope_theta)
            k = rope(k, qpos, cfg.rope_theta)
            # Attend against the PRE-write cache snapshot + the chunk's own
            # K/V. Writing first and attending the cache would evict keys
            # still inside earlier in-chunk queries' windows the moment the
            # rolling buffer wraps (any chunk >= 2 tokens) — the snapshot
            # keeps every key any query can legally see.
            k_old = _cache_read(cfg, ck, sk)
            v_old = _cache_read(cfg, cv, sv)
            if is_initialized:
                keep = min(cache_len, t)
                wslots = jnp.remainder(qpos[t - keep:], cache_len)
                pre = [None if var is None else var.value
                       for var in (ck, cv, sk, sv)]
                _cache_put_at(cfg, ck, sk, wslots, k[:, :, t - keep:, :])
                _cache_put_at(cfg, cv, sv, wslots, v[:, :, t - keep:, :])
                if prefill_len is None:
                    ci.value = start + t
                else:
                    # RIGHT-PADDED chunk (the serving engine's fixed-width
                    # prefill program): only the first prefill_len tokens
                    # are real. Their causal mask already hides the padding
                    # from every valid query (pad sits at LATER positions),
                    # but the rolling-buffer write may have landed pad K/V
                    # in slots that still hold live pre-chunk positions —
                    # restore those slots from the pre-write snapshot and
                    # advance the index by the VALID count only. Written
                    # slots are distinct (min(L,t) consecutive positions),
                    # so the scatter of per-token validity is well-defined.
                    invalid = jnp.zeros((cache_len,), bool).at[wslots].set(
                        jnp.arange(t - keep, t) >= prefill_len)
                    mask = invalid[None, None, :, None]
                    for var, old in zip((ck, cv, sk, sv), pre):
                        if var is not None:
                            var.value = jnp.where(mask, old, var.value)
                    ci.value = start + prefill_len
            # cache slots decode at idx_old = start-1 (newest pre-chunk
            # position congruent to s; same formula as single-token decode).
            # All-valid < start <= qpos, so causality is automatic there.
            slots = jnp.arange(cache_len)
            idx_old = start - 1
            p_s = idx_old - jnp.remainder(idx_old - slots, cache_len)
            ok_old = jnp.broadcast_to(p_s[None, :] >= 0, (t, cache_len))
            ok_new = qpos[None, :] <= qpos[:, None]       # intra-chunk causal
            if self.window:
                ok_old = ok_old & (p_s[None, :] > qpos[:, None] - self.window)
                ok_new = ok_new & (qpos[None, :] > qpos[:, None] - self.window)
            bias = jnp.where(jnp.concatenate([ok_old, ok_new], axis=1),
                             0.0, -jnp.inf)               # [t, L+t]
            keys = jnp.concatenate([k_old, k.astype(cfg.dtype)], axis=2)
            vals = jnp.concatenate([v_old, v.astype(cfg.dtype)], axis=2)
            qg = q.reshape(b, kv_heads, group, t, d_head)
            s = jnp.einsum("bkgtd,bkld->bkgtl", qg, keys,
                           preferred_element_type=jnp.float32)
            s = s * d_head ** -0.5 + bias[None, None, None]
            p = jax.nn.softmax(s, axis=-1)
            out = jnp.einsum("bkgtl,bkld->bkgtd", p.astype(vals.dtype),
                             vals, preferred_element_type=jnp.float32)
            out = out.astype(cfg.dtype).transpose(0, 3, 1, 2, 4)
            out = out.reshape(b, t, cfg.d_model)
            return out_dense()(out)

        if cfg.decode_len > 0 and t != 1:
            # PREFILL: the whole prompt in one causal forward (parallel,
            # MXU-shaped) instead of t sequential single-token steps. The
            # attention math is the ordinary full-sequence path below; the
            # only decode-specific work is the one-shot cache write, which
            # happens after rope (the cache stores roped K). Must be the
            # FIRST cache-mutating call (cache_index is assumed 0, matching
            # generate()'s usage); decode then continues token-by-token.
            pass  # falls through to the full-sequence path
        elif cfg.decode_len > 0:
            # KV-cache decode: one token in, attend against all cached
            # positions <= idx. Cache layout [B, H, L, D] matches training.
            # slot_decode: idx is PER-ROW [B] — rope positions, cache
            # writes and the validity mask all go row-wise, so every slot
            # of a serving batch decodes at its own position.
            b = x.shape[0]
            ck, cv, sk, sv, ci, cache_len, is_initialized = self._cache_vars(
                b, kv_heads, d_head)
            idx = ci.value
            idx_b = idx if cfg.slot_decode else idx[None]        # [B] or [1]
            q = rope(q, idx_b[:, None], cfg.rope_theta)
            k = rope(k, idx_b[:, None], cfg.rope_theta)
            if is_initialized:
                slot = jax.lax.rem(idx, jnp.int32(cache_len))
                if cfg.slot_decode:
                    # decode_active masks the whole step per row: an
                    # inactive slot (mid-prefill in the serving engine)
                    # neither writes its cache nor advances its index, so
                    # the fixed-shape all-slots step cannot corrupt it.
                    _cache_put_rows(cfg, ck, sk, slot, k,
                                    active=decode_active)
                    _cache_put_rows(cfg, cv, sv, slot, v,
                                    active=decode_active)
                    ci.value = (idx + 1 if decode_active is None
                                else idx + decode_active.astype(jnp.int32))
                else:
                    _cache_put_dyn(cfg, ck, sk, slot, k)
                    _cache_put_dyn(cfg, cv, sv, slot, v)
                    ci.value = idx + 1
            # slot s currently holds position p_s = idx - ((idx - s) mod L):
            # the newest position <= idx congruent to s. Valid iff p_s >= 0.
            # This single formula covers both layouts — unwritten slots of
            # the plain cache (s > idx) get p_s < 0, and a full rolling
            # buffer keeps exactly the last L = window positions. (It is
            # also why slot_decode needs no cache clearing on slot reuse:
            # resetting a row's index to 0 invalidates every stale slot.)
            slots = jnp.arange(cache_len)
            p_s = idx_b[:, None] - jnp.remainder(
                idx_b[:, None] - slots[None, :], cache_len)
            bias = jnp.where(p_s >= 0, 0.0, -jnp.inf)            # [B|1, L]
            # Grouped attention straight against the un-expanded cache:
            # materializing expand_kv(cache) would re-read group x the cache
            # bytes per token per layer — the exact cost GQA removes. Query
            # head h = kv*group + g reads shared head kv.
            keys = _cache_read(cfg, ck, sk)
            vals = _cache_read(cfg, cv, sv)
            qg = q[:, :, 0, :].reshape(b, kv_heads, group, d_head)
            s = jnp.einsum("bkgd,bkld->bkgl", qg, keys,
                           preferred_element_type=jnp.float32)
            s = s * d_head ** -0.5 + bias[:, None, None, :]
            p = jax.nn.softmax(s, axis=-1)  # >=1 valid key: no dead rows
            out = jnp.einsum("bkgl,bkld->bkgd", p.astype(vals.dtype),
                             vals, preferred_element_type=jnp.float32)
            out = out.astype(cfg.dtype).reshape(b, 1, cfg.d_model)
            return out_dense()(out)

        seq_sharded = (self.mesh is not None
                       and self.mesh.shape.get("seq", 1) > 1)
        impl = effective_attn_impl(cfg.attn_impl, seq_sharded)

        if self.manual_seq:
            # t is the LOCAL shard length; global positions via axis index
            positions = jax.lax.axis_index("seq") * t + jnp.arange(t)
        elif impl == "zigzag" and seq_sharded:
            # rows arrive in the zigzag layout (the data layer permuted
            # them; see zigzag_batch) — RoPE needs their GLOBAL positions,
            # which are exactly the permutation values.
            positions = att.zigzag_permutation(t, self.mesh.shape["seq"])
        else:
            positions = jnp.arange(t)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        if cfg.decode_len > 0:
            # prefill cache write: the last min(L, t) roped-K / V rows land
            # at their rolling slots (slot = pos % L, same layout the
            # single-token branch maintains) and cache_index advances by t.
            # K/V are still UNexpanded here — the cache holds kv_heads.
            ck, cv, sk, sv, ci, cache_len, is_initialized = self._cache_vars(
                x.shape[0], kv_heads, d_head)
            # One-shot prefill only: rope used positions 0..t-1 and the
            # slot math below assumes the sequence starts at 0, so a
            # multi-token apply on an ALREADY-ADVANCED cache would corrupt
            # it. The index is traced under jit (generate() upholds the
            # invariant by construction there), but eager misuse is caught.
            if (is_initialized
                    and not isinstance(ci.value, jax.core.Tracer)
                    and int(ci.value) != 0):
                raise ValueError(
                    "multi-token decode apply needs an EMPTY cache (one-"
                    "shot prefill); to continue an advanced cache use "
                    "GPTConfig(chunked_prefill=True) / "
                    "generate(prefill_chunk=...)")
            if is_initialized:
                keep = min(cache_len, t)
                slots = jnp.remainder(jnp.arange(t - keep, t), cache_len)
                _cache_put_at(cfg, ck, sk, slots, k[:, :, t - keep:, :])
                _cache_put_at(cfg, cv, sv, slots, v[:, :, t - keep:, :])
                ci.value = ci.value + t
        # expand AFTER rope (rope on kv_heads is cheaper); the repeat is a
        # transient — cache/params only ever hold kv_heads. The seq-sharded
        # ring skips it entirely: ring_attention folds query groups into
        # rows so the UNEXPANDED K/V ride the ring (group x less ICI).
        ring_gqa = (((impl == "ring" and seq_sharded) or self.manual_seq)
                    and not self.window and group > 1)
        if not ring_gqa:
            k, v = expand_kv(k), expand_kv(v)

        if self.window and seq_sharded and impl == "zigzag":
            raise ValueError(
                f"attn_window={self.window} is not supported with "
                "seq-sharded zigzag (the permuted layout breaks locality); "
                "use attn_impl=ring — windowed seq sharding routes to halo "
                "attention, which is already load-balanced")
        if self.manual_seq:
            # PP x SP: per-shard collectives inside the enclosing manual
            # context — windowed layers fetch one neighbor halo, full
            # layers ride the ring (unexpanded GQA K/V). Falls through to
            # the shared projection tail below.
            if self.window:
                out = att.halo_attention(q, k, v, window=self.window)
            else:
                out = att.ring_attention(q, k, v, causal=True)
        elif impl == "zigzag":
            if seq_sharded:
                out = att.zigzag_ring_attention_sharded(q, k, v, self.mesh)
            else:
                out = att.dense_attention(q, k, v, causal=True,
                                          window=self.window)
        elif impl == "ring":
            if self.window and seq_sharded:
                # windowed + seq-sharded: halo attention — one neighbor-
                # tail ppermute instead of rotating every K/V shard
                out = att.halo_attention_sharded(q, k, v, self.mesh,
                                                 window=self.window)
            elif self.window:
                # ring's own seq=1 fallback is windowless dense — route the
                # window explicitly rather than silently train full-causal
                out = att.dense_attention(q, k, v, causal=True,
                                          window=self.window)
            else:
                out = att.ring_attention_sharded(q, k, v, self.mesh,
                                                 causal=True)
        elif impl == "flash":
            out = fa.flash_attention_sharded(
                q, k, v, self.mesh, causal=True, window=self.window,
                block_h=cfg.flash_block_h,
                interpret=jax.default_backend() != "tpu")
        else:
            out = att.dense_attention(q, k, v, causal=True,
                                      window=self.window)
        out = out.transpose(0, 2, 1, 3).reshape(x.shape[0], t, cfg.d_model)
        out = out_dense()(out)
        return nn.Dropout(cfg.dropout)(out, deterministic=deterministic)


class Block(nn.Module):
    cfg: GPTConfig
    mesh: Optional[Mesh]
    use_moe: bool
    window: int  # no default — see CausalSelfAttention.window
    manual_seq: bool = False  # see CausalSelfAttention.manual_seq

    @nn.compact
    def __call__(self, x, deterministic: bool, prefill_len=None,
                 decode_active=None):
        cfg = self.cfg
        overlap = (cfg.tp_overlap and self.mesh is not None
                   and not self.manual_seq)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x)
        x = x + CausalSelfAttention(cfg, self.mesh, self.window,
                                    manual_seq=self.manual_seq,
                                    name="attention")(h, deterministic,
                                                      prefill_len,
                                                      decode_active)
        if overlap:
            x = comms.tp_token_sharded(x, self.mesh)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x)
        if self.use_moe:
            y = moe_lib.SwitchFFN(cfg.d_model, cfg.d_ff, cfg.moe,
                                  dtype=cfg.dtype, name="moe")(h)
        else:
            # the Megatron pair (collective matmuls under overlap; gelu
            # runs on the feature-sharded activations in between, and the
            # residual stream stays token-sharded over ('seq','model'))
            y = comms.TpDense(cfg.d_ff, self.mesh, "column",
                              overlap=overlap, dtype=cfg.dtype,
                              precision=cfg.matmul_precision,
                              name="mlp_in")(h)
            y = nn.gelu(y, approximate=True)
            y = comms.TpDense(cfg.d_model, self.mesh, "row",
                              overlap=overlap, dtype=cfg.dtype,
                              precision=cfg.matmul_precision,
                              name="mlp_out")(y)
        y = nn.Dropout(cfg.dropout)(y, deterministic=deterministic)
        if overlap:
            # keep the residual stream in the Megatron-SP token-sharded
            # layout between blocks (comms.tp_token_sharded docstring)
            return comms.tp_token_sharded(x + y, self.mesh)
        return x + y


class GPT(nn.Module):
    """Decoder-only LM. Input ids [B,T] → logits [B,T,V] (or the pre-head
    hidden states with ``return_hidden=True`` — the vocab-chunked loss
    path applies the lm_head itself, fused chunk by chunk)."""

    cfg: GPTConfig
    mesh: Optional[Mesh] = None

    @nn.compact
    def __call__(self, input_ids, *, deterministic: bool = True,
                 return_hidden: bool = False, prefill_len=None,
                 decode_active=None):
        cfg = self.cfg
        overlap = cfg.tp_overlap and self.mesh is not None
        x = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="token_embed")(input_ids)
        if overlap:
            # pin the embed OUTPUT to the baseline batch layout first (the
            # vocab-sharded masked-lookup + psum spelling, no table
            # gather), then enter the Megatron-SP layout with a local
            # slice below.
            x = comms.tp_activation_gathered(x, self.mesh)
        x = nn.Dropout(cfg.dropout)(x, deterministic=deterministic)
        if overlap:
            x = comms.tp_token_sharded(x, self.mesh)
        block = Block
        if cfg.remat:
            block = nn.remat(Block, static_argnums=(2,))
        for i in range(cfg.layers):
            use_moe = cfg.moe_every > 0 and (i + 1) % cfg.moe_every == 0
            x = block(cfg, self.mesh, use_moe, cfg.layer_window(i),
                      name=f"layer_{i}")(x, deterministic, prefill_len,
                                         decode_active)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        if return_hidden:
            # the chunked-loss path applies lm_head itself; the Dense
            # below must still exist at init time, which it does — init
            # always runs with return_hidden=False
            return x
        if overlap:
            # the ONE gather the head genuinely needs (Megatron-SP): the
            # ACTIVATIONS come back over the TP axis for the vocab-parallel
            # head matmul — never the [D, V] head kernel.
            x = comms.tp_activation_gathered(x, self.mesh)
        logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=jnp.float32,
                          param_dtype=jnp.float32, name="lm_head")(x)
        return logits


def zigzag_batch(batch: dict, seq_shards: int) -> dict:
    """Permute a CLM batch into the zigzag layout (host-side numpy).

    With ``attn_impl="zigzag"`` the whole model runs in the permuted order
    (per-token CE is order-invariant; RoPE gets the true global positions
    inside the attention module), so permuting input_ids and labels at the
    data layer is the ONLY change training needs.
    """
    import numpy as np

    from dtf_tpu.ops.attention import zigzag_permutation

    t = batch["input_ids"].shape[1]
    perm = np.asarray(zigzag_permutation(t, seq_shards))
    return {**batch, "input_ids": batch["input_ids"][:, perm],
            "labels": batch["labels"][:, perm]}


def make_init(cfg: GPTConfig, mesh: Optional[Mesh] = None, seq_len: int = 128):
    model = GPT(cfg, mesh)
    b = mesh.shape.get("data", 1) if mesh is not None else 1

    def init_fn(rng):
        ids = jnp.zeros((b, seq_len), jnp.int32)
        return model.init(rng, ids, deterministic=True)

    return model, init_fn


def cache_shardings(mesh: Mesh, cache_shapes):
    """NamedSharding tree for a KV-cache collection: [B, H, L, D] leaves
    shard batch over ``data`` and heads over ``model`` (the layout
    ``decode_len`` exists for — each TP shard serves its own heads, each DP
    shard its own sequences); scalar indices replicate."""
    from jax.sharding import NamedSharding

    def leaf(s):
        if getattr(s, "ndim", 0) == 4:
            return NamedSharding(mesh, P("data", "model", None, None))
        return NamedSharding(mesh, P())

    return jax.tree.map(leaf, cache_shapes)


def filter_logits(logits: jax.Array, *, top_k: int = 0,
                  top_p: float = 1.0) -> jax.Array:
    """Top-k / nucleus (top-p) filtering: disallowed logits become -inf.

    Static shapes throughout (sorts + thresholds, no gather of a dynamic
    count), so it jits and vmaps cleanly inside the decode scan. ``top_k=0``
    and ``top_p=1.0`` are no-ops; the highest-probability token is always
    kept. k-filter applies first, then the nucleus is computed over the
    k-survivors (the standard sequential-warper composition). Exactly k
    tokens survive the k-filter — ties at the k-th logit are broken by
    token index (lower index wins), matching sorted-order semantics rather
    than keeping every tied token. Callers should pass ALREADY-TEMPERED
    logits (logits/temperature) so the nucleus reflects the distribution
    actually sampled — ``generate`` does.
    """
    if top_k <= 0 and top_p >= 1.0:
        return logits
    vocab = logits.shape[-1]
    desc = jnp.sort(logits, axis=-1)[..., ::-1]   # serves the top-p pass
    if top_k > 0:
        k = min(top_k, vocab)
        # rank via double argsort (stable ⇒ ties broken by token index);
        # a plain `logits < desc[k-1]` threshold would keep EVERY token
        # tied with the k-th largest (ADVICE r3)
        order = jnp.argsort(-logits, axis=-1)
        ranks = jnp.argsort(order, axis=-1)       # 0 = largest logit
        logits = jnp.where(ranks < k, logits, -jnp.inf)
        desc = jnp.where(jnp.arange(vocab) < k, desc, -jnp.inf)
    if top_p < 1.0:
        probs = jax.nn.softmax(desc, axis=-1)     # -inf rows contribute 0
        cum = jnp.cumsum(probs, axis=-1)
        keep = (cum - probs) < top_p          # first excluded crosses top_p
        thresh = jnp.min(jnp.where(keep, desc, jnp.inf), axis=-1,
                         keepdims=True)
        logits = jnp.where(logits < thresh, -jnp.inf, logits)
    return logits


def filter_logits_dynamic(logits: jax.Array, *, top_k: jax.Array,
                          top_p: jax.Array) -> jax.Array:
    """:func:`filter_logits` with TRACED ``top_k`` / ``top_p`` scalars.

    The serving engine (:mod:`dtf_tpu.serve`) folds per-slot sampling
    params into ONE fixed-shape decode program (vmapped over slots), so
    k/p arrive as runtime values, not Python ints. Same semantics as the
    static path — including its no-op gates: the k-filter is selected only
    where ``top_k > 0`` and the nucleus only where ``top_p < 1``, so a
    slot running (0, 1.0) sees BIT-identical logits to an offline
    ``generate()`` with the filters off (the engine/offline parity
    contract), rather than "numerically equivalent" recomputed ones.
    """
    vocab = logits.shape[-1]
    desc = jnp.sort(logits, axis=-1)[..., ::-1]
    k = jnp.clip(top_k, 1, vocab)             # only read where top_k > 0
    order = jnp.argsort(-logits, axis=-1)
    ranks = jnp.argsort(order, axis=-1)       # 0 = largest logit
    use_k = top_k > 0
    logits = jnp.where(use_k & (ranks >= k), -jnp.inf, logits)
    desc = jnp.where(use_k & (jnp.arange(vocab) >= k), -jnp.inf, desc)
    probs = jax.nn.softmax(desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < top_p
    thresh = jnp.min(jnp.where(keep, desc, jnp.inf), axis=-1, keepdims=True)
    return jnp.where((top_p < 1.0) & (logits < thresh), -jnp.inf, logits)


def generate(model: GPT, params, prompt: jax.Array, n_new: int,
             *, rng: Optional[jax.Array] = None,
             temperature: float = 0.0,
             top_k: int = 0, top_p: float = 1.0,
             eos_id: Optional[int] = None, pad_id: int = 0,
             prefill_chunk: int = 0,
             mesh: Optional[Mesh] = None) -> jax.Array:
    """Autoregressive decode: one-pass prefill + a single-token ``lax.scan``.

    ``model.cfg.decode_len`` must cover prompt+new tokens. ``prompt``
    [B, T_p] int32; returns [B, T_p + n_new]. Greedy when temperature==0,
    else temperature sampling with optional ``top_k`` / nucleus ``top_p``
    filtering (:func:`filter_logits`). The prompt is PREFILLED in one
    parallel causal forward that writes the KV cache (MXU-shaped work,
    not T_p sequential steps); generation is then a jittable scan with the
    cache as carried state, one token per step — the standard TPU serving
    shape.

    ``eos_id``: once a sequence emits it, every later token is ``pad_id``
    (the scan stays fixed-length — static shapes — but the output is
    properly terminated per sequence).

    ``prefill_chunk``: 0 = the whole prompt in one forward (fastest —
    flash-kernel attention). >0 = prefill in chunks of that many tokens
    via the cache-continuing path (``GPTConfig.chunked_prefill``): peak
    prefill activation memory is O(chunk·(L+chunk)) instead of O(T_p²),
    the knob for prompts whose one-shot score matrix doesn't fit.
    Matches one-shot prefill logits exactly (parity-tested), including
    rolling-window caches that wrap mid-prompt — at full-precision cache
    dtypes. With ``kv_cache_dtype="int8"`` chunked prefill reads
    pre-chunk keys back DEQUANTIZED while one-shot attends raw K/V, so
    parity is within quantization tolerance, not exact (tested).

    ``mesh``: shard the decode — the KV cache lands P('data','model')
    (batch over data shards, heads over TP shards; see
    :func:`cache_shardings`), the prompt P('data'). Params keep whatever
    sharding the caller placed them with (e.g. :data:`tp_rules`); GSPMD
    propagates through the scan, so TP decode needs no other change.
    """
    cfg = model.cfg
    b, t_p = prompt.shape
    total = t_p + n_new
    if n_new < 1:
        raise ValueError(f"n_new={n_new} must be >= 1")
    if cfg.decode_len < total:
        raise ValueError(
            f"decode_len={cfg.decode_len} < prompt+new={total}")
    if rng is None:
        rng = jax.random.PRNGKey(0)
    if mesh is not None:
        if b % mesh.shape.get("data", 1):
            raise ValueError(f"decode batch {b} not divisible by the data "
                             f"axis ({mesh.shape.get('data', 1)})")
        kv_heads = cfg.kv_heads_resolved
        if cfg.heads % mesh.shape.get("model", 1):
            raise ValueError(f"{cfg.heads} heads not divisible by the model "
                             f"axis ({mesh.shape.get('model', 1)})")
        if kv_heads % mesh.shape.get("model", 1):
            raise ValueError(f"{kv_heads} kv_heads not divisible by the "
                             f"model axis ({mesh.shape.get('model', 1)}) — "
                             "the cache shards heads over 'model'")

    # Build an all-zeros cache (index 0, no slots written) without
    # materialising a throwaway parameter set: eval_shape traces init
    # abstractly, then we allocate zeros matching the cache collection.
    shapes = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((b, 1), jnp.int32)))
    if mesh is not None:
        csh = cache_shardings(mesh, shapes["cache"])
        # sharding-aware allocation: each device materializes only its
        # shard — the global-zeros-then-reshard form would OOM device 0 for
        # exactly the cache sizes this path exists for.
        cache0 = jax.tree.map(
            lambda s, sh: jnp.zeros(s.shape, s.dtype, device=sh),
            shapes["cache"], csh)
        prompt = jax.device_put(
            prompt, jax.sharding.NamedSharding(mesh, P("data", None)))
    else:
        cache0 = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                              shapes["cache"])

    def pick(nxt_logits, sub):
        if temperature > 0.0:
            # temper FIRST so the nucleus is built from the distribution
            # actually sampled (the standard warper ordering).
            filtered = filter_logits(nxt_logits / temperature,
                                     top_k=top_k, top_p=top_p)
            nxt = jax.random.categorical(sub, filtered, -1)
        else:
            nxt = jnp.argmax(nxt_logits, -1)
        return nxt.astype(jnp.int32)

    logits, cache = _prefill(model, params, cache0, prompt, prefill_chunk)
    rng, sub = jax.random.split(rng)
    tok0 = pick(logits[:, -1], sub)
    # EOS semantics: a sequence that has EMITTED eos_id keeps stepping (the
    # scan is fixed-length — the standard TPU shape) but every later token
    # is pad_id. done flips AFTER the eos token itself is kept.
    done0 = (tok0 == eos_id) if eos_id is not None else None

    def body(carry, _):
        cache, tok, done, rng = carry
        logits, mut = model.apply(
            {"params": params, "cache": cache}, tok[:, None],
            deterministic=True, mutable=["cache"])
        rng, sub = jax.random.split(rng)
        nxt = pick(logits[:, 0], sub)
        if eos_id is not None:
            nxt = jnp.where(done, jnp.int32(pad_id), nxt)
            done = done | (nxt == eos_id)
        return (mut["cache"], nxt, done, rng), nxt

    (_, _, _, _), toks = jax.lax.scan(
        body, (cache, tok0, done0, rng), None, length=n_new - 1)
    out = jnp.concatenate(
        [prompt, tok0[:, None], toks.T.astype(jnp.int32)], axis=1)
    return out


def _prefill(model: GPT, params, cache0, prompt, prefill_chunk: int):
    """The shared prompt prefill: one parallel causal forward that writes
    the KV cache (t_p MXU-shaped steps collapse into one), or — with
    ``prefill_chunk`` — a static Python loop of cache-continuing applies
    at O(chunk·(L+chunk)) peak memory. Returns (logits, cache)."""
    cfg = model.cfg
    t_p = prompt.shape[1]
    if prefill_chunk > 0:
        cmodel = GPT(dataclasses.replace(cfg, chunked_prefill=True),
                     model.mesh)
        cache, logits = cache0, None
        for s0 in range(0, t_p, prefill_chunk):
            logits, mut = cmodel.apply(
                {"params": params, "cache": cache},
                prompt[:, s0:s0 + prefill_chunk],
                deterministic=True, mutable=["cache"])
            cache = mut["cache"]
        return logits, cache
    logits, mut = model.apply({"params": params, "cache": cache0},
                              prompt, deterministic=True,
                              mutable=["cache"])
    return logits, mut["cache"]


#: cache-collection leaves whose leading dim is the batch (beam search
#: clones and reorders exactly these); every other cache key must appear in
#: _NON_BATCH_CACHE_KEYS, so an unrecognized leaf fails loudly instead of
#: silently riding the beams unreordered. The SAME set is the paged-leaf
#: registry: every batch-led leaf is [rows, H, L, D]-shaped, so the prefix
#: page cache (dtf_tpu/serve/pages.py) reads/writes fixed-size windows of
#: the L axis through :func:`cache_load_pages` / :func:`cache_save_pages` —
#: a new cache variable added to _cache_vars must be classified here or
#: every consumer (beams, serve slot slicing, pages) fails loudly at once.
_BATCH_LED_CACHE_KEYS = frozenset(
    {"cached_key", "cached_value", "key_scale", "value_scale"})
_NON_BATCH_CACHE_KEYS = frozenset({"cache_index"})


def _path_key(k) -> str:
    return getattr(k, "key", str(k))


def _cache_leaf_name(path) -> str:
    return _path_key(path[-1])


def _set_by_path(tree: dict, path, leaf) -> None:
    node = tree
    for k in path[:-1]:
        node = node.setdefault(_path_key(k), {})
    node[_path_key(path[-1])] = leaf


def _get_by_path(tree, path):
    node = tree
    for k in path:
        node = node[_path_key(k)]
    return node


def _paged_leaf_check(name: str) -> bool:
    """True for paged leaves, False for index leaves; loud otherwise —
    the completeness contract of ``_BATCH_LED_CACHE_KEYS``."""
    if name in _NON_BATCH_CACHE_KEYS:
        return False
    if name not in _BATCH_LED_CACHE_KEYS:
        raise ValueError(
            f"unknown cache leaf {name!r}: add it to "
            "_BATCH_LED_CACHE_KEYS or _NON_BATCH_CACHE_KEYS so the "
            "page cache knows whether to page it")
    return True


def cache_index_of(cache) -> jax.Array:
    """The cache's position counter — the first ``cache_index`` leaf.
    Every layer's counter advances in lockstep (each apply touches all
    layers equally), so one leaf is the whole cache's position."""
    for path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]:
        if _cache_leaf_name(path) == "cache_index":
            return leaf
    raise ValueError("cache has no cache_index leaf")


def cache_rollback(cache, new_index, active=None):
    """Set every layer's ``cache_index`` to ``new_index`` — the
    speculative-decode ROLLBACK: after a verify pass wrote k+1 candidate
    positions, the accepted boundary is a per-row index assignment and
    nothing else. Rejected-tail K/V stays in the cache as stale bytes;
    the validity bias (``p_s >= 0``) derives visibility from the index,
    so no clearing pass exists to forget. ``active`` (optional [S] bool)
    preserves inactive rows' current per-leaf counters — a mid-prefill
    slot's index must not be clobbered by its neighbors' verify tick."""
    def leaf(path, x):
        if _cache_leaf_name(path) != "cache_index":
            return x
        ni = jnp.broadcast_to(new_index, x.shape).astype(x.dtype)
        return jnp.where(active, ni, x) if active is not None else ni

    return jax.tree_util.tree_map_with_path(leaf, cache)


def draft_truncate(cfg: GPTConfig, params, n_layers: int
                   ) -> tuple[GPTConfig, dict]:
    """An EARLY-EXIT draft from a trained checkpoint: the first
    ``n_layers`` blocks of ``params`` (plus embed / final LN / head)
    reused as the speculative draft model — a draft without a second
    checkpoint. Proposal quality is what the truncated stack gives (the
    usual early-exit trade); correctness never depends on it — the
    verifier samples every delivered token. The returned tree SHARES the
    kept leaves with ``params`` (no copy)."""
    if not 1 <= n_layers < cfg.layers:
        raise ValueError(
            f"draft n_layers={n_layers} must be in [1, {cfg.layers}) — "
            "a draft at full depth proposes at full cost")
    if cfg.moe_every:
        raise ValueError("draft_truncate does not support MoE configs "
                         "(the decode stack has no MoE path)")
    dcfg = dataclasses.replace(cfg, layers=n_layers)
    keep = {"token_embed", "ln_f", "lm_head"} | {
        f"layer_{i}" for i in range(n_layers)}
    missing = keep - set(params)
    if missing:
        raise ValueError(f"params tree is missing {sorted(missing)} — "
                         "not a GPT checkpoint?")
    return dcfg, {k: params[k] for k in sorted(keep)}


def cache_load_pages(cache, pool, slot, page_ids, n_valid):
    """The paged READ view: gather pool pages ``page_ids[:n_valid]`` into
    the leading positions of slot ``slot`` of every batch-led cache leaf,
    in ONE fixed-shape op (the serving prefix cache admits a whole pinned
    chain per compiled call — per-page dispatches would cost as much host
    overhead as the transformer chunks they replace).

    ``cache`` leaves are ``[S, H, L, D]``, ``pool`` leaves ``[P, H, p, D]``
    at the same tree paths with ``L = len(page_ids) * p`` (pages tile the
    cache — the engine validates ``max_len % page_size == 0``); entries of
    ``page_ids`` at or past ``n_valid`` are ignored (positions keep their
    current contents). Copies are bitwise: int8 caches bring their scale
    leaves through the same paths."""
    def per_leaf(path, leaf):
        if not _paged_leaf_check(_cache_leaf_name(path)):
            return leaf
        pleaf = _get_by_path(pool, path)
        p = pleaf.shape[2]
        m = leaf.shape[2] // p
        # OOB-safe: ids past n_valid may be anything in [0, P) — their
        # gathered rows are masked back to the current contents below
        pages = pleaf[jnp.clip(page_ids, 0, pleaf.shape[0] - 1)]
        flat = pages.transpose(1, 0, 2, 3).reshape(
            leaf.shape[1], m * p, leaf.shape[3])
        cur = jax.lax.dynamic_slice(
            leaf, (slot, 0, 0, 0), (1,) + leaf.shape[1:])[0]
        mask = (jnp.arange(m * p) < n_valid * p)[None, :, None]
        row = jnp.where(mask, flat, cur)
        return jax.lax.dynamic_update_slice(leaf, row[None],
                                            (slot, 0, 0, 0))

    return jax.tree_util.tree_map_with_path(per_leaf, cache)


def cache_save_pages(cache, pool, slot, page_ids):
    """The paged WRITE view: scatter slot ``slot``'s cache row, split into
    pages, to pool entries ``page_ids`` in ONE fixed-shape op. Page ``j``
    lands at ``page_ids[j]``; point unwanted pages at an out-of-range id
    (``>= P``) — drop-mode scatter discards them, the fixed-shape spelling
    of "save only the new pages". Returns the updated pool."""
    def per_leaf(path, pleaf):
        if not _paged_leaf_check(_cache_leaf_name(path)):
            return pleaf
        leaf = _get_by_path(cache, path)
        p = pleaf.shape[2]
        m = leaf.shape[2] // p
        row = jax.lax.dynamic_slice(
            leaf, (slot, 0, 0, 0), (1,) + leaf.shape[1:])[0]
        pages = row.reshape(leaf.shape[1], m, p,
                            leaf.shape[3]).transpose(1, 0, 2, 3)
        return pleaf.at[page_ids].set(pages, mode="drop")

    return jax.tree_util.tree_map_with_path(per_leaf, pool)


def generate_beam(model: GPT, params, prompt: jax.Array, n_new: int, *,
                  num_beams: int = 4,
                  eos_id: Optional[int] = None, pad_id: int = 0,
                  length_penalty: float = 0.0,
                  prefill_chunk: int = 0) -> jax.Array:
    """Beam-search decode: the deterministic search the sampling family
    (:func:`generate`) doesn't cover. [B, T_p] -> [B, T_p + n_new].

    Standard fixed-width beam search in one ``lax.scan`` (static shapes):
    the cache runs at batch B*k; every step expands k beams x V tokens,
    keeps the global top-k per batch row, and REORDERS the KV cache along
    the batch axis to follow the surviving beams (the per-step gather is
    beam search's inherent cost). Finished beams (``eos_id``) are frozen:
    their only continuation is ``pad_id`` at zero added log-prob, so
    their score stays comparable while the scan stays fixed-length.
    ``length_penalty`` alpha rescores finals by ``score / len**alpha``
    (0 = pure sum-logprob; GNMT-style normalization at 1.0). The emitted
    (parent, token) lattice is backtraced after the scan — O(n) memory,
    no in-scan sequence buffers.

    Composes with ``prefill_chunk`` (shared :func:`_prefill`) and any
    ``model.cfg`` cache variant (GQA / rolling window / int8 — batch-led
    leaves are selected by key path, see ``_BATCH_LED_CACHE_KEYS``). Sharded
    (mesh) decode is not wired for beams; shard the batch outside.
    """
    cfg = model.cfg
    b, t_p = prompt.shape
    k = num_beams
    if k < 1:
        raise ValueError(f"num_beams={k} must be >= 1")
    if n_new < 1:
        raise ValueError(f"n_new={n_new} must be >= 1")
    if cfg.decode_len < t_p + n_new:
        raise ValueError(
            f"decode_len={cfg.decode_len} < prompt+new={t_p + n_new}")

    # Prefill ONCE at batch B (k identical beams would pay k-fold
    # redundant prompt compute and O(T_p^2) activation memory), then
    # clone the cache k-fold into the beam-expanded layout: rows
    # [b*k + i] are batch b's beams.
    shapes = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((b, 1), jnp.int32)))
    cache0 = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                          shapes["cache"])
    logits, cache = _prefill(model, params, cache0, prompt, prefill_chunk)

    # Batch-led cache leaves are selected BY KEY PATH, not by leading-dim
    # size: a future leaf with a colliding shape[0] must not be silently
    # (mis)reordered, and a renamed batch-led leaf must fail loudly here
    # rather than ride the beams unreordered. The shape check is demoted to
    # an assertion on the selected leaves.
    def _map_batch_led(fn, cache, lead):
        def per_leaf(path, leaf):
            name = getattr(path[-1], "key", str(path[-1]))
            if name in _BATCH_LED_CACHE_KEYS:
                assert getattr(leaf, "ndim", 0) >= 1 and \
                    leaf.shape[0] == lead, (
                        f"cache leaf {name!r} expected leading dim "
                        f"{lead}, got {getattr(leaf, 'shape', None)}")
                return fn(leaf)
            if name not in _NON_BATCH_CACHE_KEYS:
                # a hard error, not an assert: silently riding the beams
                # unreordered corrupts decode output (and -O strips asserts)
                raise ValueError(
                    f"unknown cache leaf {name!r}: add it to "
                    "_BATCH_LED_CACHE_KEYS or _NON_BATCH_CACHE_KEYS so "
                    "beam search knows whether to reorder it")
            return leaf

        return jax.tree_util.tree_map_with_path(per_leaf, cache)

    cache = _map_batch_led(lambda leaf: jnp.repeat(leaf, k, axis=0),
                           cache, b)
    logits = jnp.repeat(logits[:, -1:], k, axis=0)           # [B*k, 1, V]

    def reorder(cache, parent):
        rows = (jnp.arange(b)[:, None] * k + parent).reshape(-1)
        return _map_batch_led(lambda leaf: leaf[rows], cache, b * k)

    def expand(scores, logprobs, done):
        """(scores [B,k], logprobs [B,k,V], done [B,k]) -> top-k beams:
        (new scores, parent [B,k], token [B,k], new done)."""
        if eos_id is not None:
            # frozen beams continue ONLY as pad at zero added log-prob
            frozen = jnp.full(logprobs.shape[-1:], -jnp.inf
                              ).at[pad_id].set(0.0)
            logprobs = jnp.where(done[:, :, None], frozen[None, None],
                                 logprobs)
        total = scores[:, :, None] + logprobs                # [B,k,V]
        v = total.shape[-1]
        flat = total.reshape(b, k * v)
        new_scores, idx = jax.lax.top_k(flat, k)             # [B,k]
        parent = idx // v
        token = (idx % v).astype(jnp.int32)
        new_done = jnp.take_along_axis(done, parent, 1)
        if eos_id is not None:
            new_done = new_done | (token == eos_id)
        return new_scores, parent, token, new_done

    logprobs0 = jax.nn.log_softmax(
        logits[:, -1].astype(jnp.float32).reshape(b, k, -1))
    # (the repeat above makes every beam's row identical; the score mask
    # below is what breaks the symmetry)
    # beams 1..k-1 start at -inf so the first top-k comes from beam 0
    # (all beams are identical clones until they diverge here)
    scores0 = jnp.where(jnp.arange(k)[None, :] == 0, 0.0, -jnp.inf)
    scores0 = jnp.broadcast_to(scores0, (b, k))
    done0 = jnp.zeros((b, k), bool)
    scores, parent0, tok0, done = expand(scores0, logprobs0, done0)
    cache = reorder(cache, parent0)
    lens0 = jnp.ones((b, k), jnp.float32)                    # tokens emitted

    def body(carry, _):
        cache, scores, tok, done, lens = carry
        logits, mut = model.apply(
            {"params": params, "cache": cache}, tok.reshape(b * k, 1),
            deterministic=True, mutable=["cache"])
        logprobs = jax.nn.log_softmax(
            logits[:, 0].astype(jnp.float32).reshape(b, k, -1))
        new_scores, parent, token, new_done = expand(scores, logprobs, done)
        lens = jnp.take_along_axis(lens, parent, 1) + jnp.where(
            jnp.take_along_axis(done, parent, 1), 0.0, 1.0)
        cache = reorder(mut["cache"], parent)
        return ((cache, new_scores, token, new_done, lens),
                (parent, token))

    (cache, scores, tok, done, lens), (parents, tokens) = jax.lax.scan(
        body, (cache, scores, tok0, done, lens0), None, length=n_new - 1)
    # prepend step 1 so the backtrace covers every emitted token
    parents = jnp.concatenate([parent0[None], parents], axis=0)  # [S,B,k]
    tokens = jnp.concatenate([tok0[None], tokens], axis=0)       # [S,B,k]

    final = scores
    if length_penalty:
        final = scores / jnp.maximum(lens, 1.0) ** length_penalty
    best = jnp.argmax(final, axis=1)                             # [B]

    def back(idx, pt):
        par, tk = pt                                             # [B,k]
        t = jnp.take_along_axis(tk, idx[:, None], 1)[:, 0]
        nidx = jnp.take_along_axis(par, idx[:, None], 1)[:, 0]
        return nidx, t

    _, toks = jax.lax.scan(back, best, (parents, tokens), reverse=True)
    return jnp.concatenate([prompt, toks.T.astype(jnp.int32)], axis=1)


def make_eval(model: GPT, *, loss_chunk: int = 0,
              loss_chunk_tokens: int = 0, loss_pallas: bool = False):
    """Held-out eval: mean next-token CE and perplexity (ignore -100).

    ``loss_chunk`` / ``loss_chunk_tokens`` / ``loss_pallas``: same
    fused-CE options as :func:`make_loss` — a training run that only
    fits with a fused loss would otherwise OOM at its first EVAL (full
    [B,T,V] logits)."""
    fused = _fused_ce(loss_chunk, loss_chunk_tokens, loss_pallas,
                      model.mesh)

    def eval_fn(params, extra, batch):
        cfg = model.cfg
        out = model.apply({"params": params}, batch["input_ids"],
                          deterministic=True,
                          mutable=["losses"] if cfg.moe_every else False,
                          return_hidden=fused is not None)
        y = out[0] if cfg.moe_every else out
        if fused is not None:
            loss, _ = fused(y, params["lm_head"]["kernel"], batch["labels"])
        else:
            loss, _ = softmax_cross_entropy(y, batch["labels"],
                                            ignore_index=-100)
        return {"eval_loss": loss, "eval_ppl": jnp.exp(loss)}

    return eval_fn


def _fused_ce(loss_chunk: int, loss_chunk_tokens: int,
              loss_pallas: bool = False, mesh=None):
    """Resolve the head-fused CE options to one callable (or None for
    the monolithic-logits path). Vocab chunking bounds memory at
    O(N·chunk) with an online-lse scan; token chunking bounds it at
    O(chunk·V) with a plain CE per token block — the faster shape on
    chip (losses.py: token_chunked_lm_cross_entropy docstring); the
    pallas kernel keeps logits in VMEM tiles entirely (ops/fused_ce.py
    — the flash-attention move applied to the LM head)."""
    if sum(map(bool, (loss_chunk, loss_chunk_tokens, loss_pallas))) > 1:
        raise ValueError("loss_chunk (vocab), loss_chunk_tokens and "
                         "loss_pallas are mutually exclusive")
    from dtf_tpu.ops.losses import (chunked_lm_cross_entropy,
                                    token_chunked_lm_cross_entropy)
    if loss_pallas:
        from dtf_tpu.ops.fused_ce import pallas_lm_cross_entropy_sharded

        def pallas_ce(y, w, lab):
            # the shard_map boundary lives in the op (like flash's
            # _sharded variants): a bare pallas_call under jit would
            # all-gather the DP/SP-sharded tokens and run redundantly
            mean, n = pallas_lm_cross_entropy_sharded(
                y, w, lab, mesh, ignore_index=-100,
                interpret=jax.default_backend() != "tpu")
            return mean, n

        return pallas_ce
    if loss_chunk_tokens:
        return lambda y, w, lab: token_chunked_lm_cross_entropy(
            y, w, lab, chunk=loss_chunk_tokens, ignore_index=-100)
    if loss_chunk:
        return lambda y, w, lab: chunked_lm_cross_entropy(
            y, w, lab, chunk=loss_chunk, ignore_index=-100)
    return None


def make_loss(model: GPT, *, loss_chunk: int = 0,
              loss_chunk_tokens: int = 0, loss_pallas: bool = False):
    """Next-token CE: batch = {"input_ids" [B,T], "labels" [B,T]} where
    labels are input_ids shifted left by the data layer (-100 = ignore).

    ``loss_chunk > 0``: compute CE fused with the lm_head in vocab chunks
    of that width (:func:`dtf_tpu.ops.losses.chunked_lm_cross_entropy`) —
    identical numbers, O(N·chunk) instead of O(N·V) live logits memory
    (the single-chip batch-size ceiling for a 50k vocab).
    ``loss_chunk_tokens > 0``: chunk TOKENS instead — O(chunk·V) live
    logits and one full-vocab MXU matmul per block, the faster chunking
    axis on chip (:func:`~dtf_tpu.ops.losses.token_chunked_lm_cross_entropy`).
    ``loss_pallas``: the Pallas fused head+CE kernel — logits live only
    in VMEM tiles (:mod:`dtf_tpu.ops.fused_ce`).
    All compose with DP/SP; under TP (lm_head sharded over 'model')
    prefer the standard path — chunk slices fight the vocab sharding.
    """
    fused = _fused_ce(loss_chunk, loss_chunk_tokens, loss_pallas,
                      model.mesh)

    def loss_fn(params, extra, batch, rng):
        cfg = model.cfg
        out = model.apply(
            {"params": params}, batch["input_ids"],
            deterministic=cfg.dropout == 0.0,
            rngs={"dropout": rng} if cfg.dropout else {},
            mutable=["losses"] if cfg.moe_every else False,
            return_hidden=fused is not None)
        y, mut = out if cfg.moe_every else (out, {})
        if fused is not None:
            loss, n = fused(y, params["lm_head"]["kernel"], batch["labels"])
        else:
            loss, n = softmax_cross_entropy(y, batch["labels"],
                                            ignore_index=-100)
        loss = loss + moe_lib.moe_aux_loss(mut, cfg.moe)
        return loss, LossAux(extra=extra, metrics={"lm_tokens": n}, weight=n)

    return loss_fn
