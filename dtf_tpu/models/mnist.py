"""MNIST softmax regression — the reference's actual workload (BASELINE config 1).

The reference builds ``y = softmax(Wx + b)`` with cross-entropy loss and
``GradientDescentOptimizer`` under ``replica_device_setter`` (SURVEY.md §1
L3). Here it's a flax module; placement is a rule set instead of a device
function, and the sync-replica aggregation comes from the shared train step.
An MLP variant is included for a non-trivial-capacity smoke model.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax
from flax import linen as nn

from dtf_tpu.core.train import LossAux


class SoftmaxRegression(nn.Module):
    """Single dense layer, exactly the reference model."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        return nn.Dense(self.num_classes, name="logits")(x)


class MLP(nn.Module):
    hidden: tuple[int, ...] = (128,)
    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        for i, h in enumerate(self.hidden):
            x = nn.relu(nn.Dense(h, name=f"hidden_{i}")(x))
        return nn.Dense(self.num_classes, name="logits")(x)


def make_model(kind: str = "softmax") -> nn.Module:
    return SoftmaxRegression() if kind == "softmax" else MLP()


def make_init(model: nn.Module, input_dim: int = 784):
    def init_fn(rng):
        return model.init(rng, jnp.zeros((1, input_dim), jnp.float32))

    return init_fn


def make_loss(model: nn.Module):
    """Mean softmax cross-entropy — mean over the *global* batch, which under
    a data-sharded batch reproduces SyncReplicasOptimizer's mean-of-replicas
    gradient (SURVEY.md §3.3)."""

    def loss_fn(params, extra, batch, rng):
        logits = model.apply({"params": params}, batch["image"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"]).mean()
        acc = jnp.mean(jnp.argmax(logits, -1) == batch["label"])
        return loss, LossAux(extra=extra, metrics={"accuracy": acc})

    return loss_fn


def make_eval(model: nn.Module):
    def eval_fn(params, extra, batch):
        logits = model.apply({"params": params}, batch["image"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"]).mean()
        acc = jnp.mean(jnp.argmax(logits, -1) == batch["label"])
        return {"eval_loss": loss, "eval_accuracy": acc}

    return eval_fn
