"""Wide&Deep recsys — BASELINE config 5 (row-sharded embedding tables).

Criteo-style CTR model: 13 dense + 26 categorical features.

- **wide**: per-bucket scalar weights (an embedding of dim 1) summed with a
  linear term on the dense features — the classic cross/linear half.
- **deep**: per-feature embeddings (row-sharded tables via
  :class:`dtf_tpu.parallel.embedding.RowShardedEmbed`) concatenated with the
  dense features into an MLP.

The reference-era version of this put every embedding table on a parameter
server and paid a gRPC gather per lookup (SURVEY.md §2c "Embedding sharding");
here tables are GSPMD row-sharded over ``model`` and lookups compile to local
gathers + one collective.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn

from dtf_tpu.core.train import LossAux
from dtf_tpu.parallel.embedding import RowShardedEmbed, embedding_rules


class WideDeep(nn.Module):
    num_sparse: int = 26
    hash_buckets: int = 1000
    embed_dim: int = 16
    mlp: tuple[int, ...] = (256, 128, 64)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, dense, sparse):
        # ---- wide: scalar weight per (feature, bucket) + linear on dense.
        wide_tables = RowShardedEmbed(
            self.num_sparse * self.hash_buckets, 1, dtype=jnp.float32,
            name="embed_tables_wide")
        offsets = jnp.arange(self.num_sparse) * self.hash_buckets
        flat_ids = sparse + offsets[None, :]          # disjoint id spaces
        wide_logit = wide_tables(flat_ids)[..., 0].sum(-1)
        wide_logit = wide_logit + nn.Dense(
            1, dtype=jnp.float32, param_dtype=jnp.float32,
            name="wide_dense")(dense)[..., 0]

        # ---- deep: shared-space embeddings → MLP.
        deep_tables = RowShardedEmbed(
            self.num_sparse * self.hash_buckets, self.embed_dim,
            dtype=self.dtype, name="embed_tables_deep")
        emb = deep_tables(flat_ids)                   # [B, F, E]
        x = jnp.concatenate(
            [emb.reshape(emb.shape[0], -1),
             dense.astype(self.dtype)], axis=-1)
        for i, h in enumerate(self.mlp):
            x = nn.relu(nn.Dense(h, dtype=self.dtype,
                                 param_dtype=jnp.float32,
                                 name=f"mlp_{i}")(x))
        deep_logit = nn.Dense(1, dtype=jnp.float32, param_dtype=jnp.float32,
                              name="deep_out")(x)[..., 0]
        return wide_logit + deep_logit


#: model-axis row sharding for both table sets.
rules = embedding_rules("model")


def make_init(model: WideDeep):
    def init_fn(rng):
        return model.init(rng, jnp.zeros((1, 13), jnp.float32),
                          jnp.zeros((1, model.num_sparse), jnp.int32))

    return init_fn


def make_eval(model: WideDeep):
    """Held-out CTR eval: logloss + accuracy + prediction/label correlation
    (the cheap jittable AUC stand-in the train metrics also use)."""

    def eval_fn(params, extra, batch):
        logits = model.apply({"params": params}, batch["dense"],
                             batch["sparse"])
        loss = optax.sigmoid_binary_cross_entropy(
            logits, batch["label"]).mean()
        acc = jnp.mean((logits > 0) == (batch["label"] > 0.5))
        corr = jnp.nan_to_num(
            jnp.corrcoef(jax.nn.sigmoid(logits), batch["label"])[0, 1])
        return {"eval_logloss": loss, "eval_accuracy": acc,
                "eval_pred_corr": corr}

    return eval_fn


def make_loss(model: WideDeep):
    def loss_fn(params, extra, batch, rng):
        logits = model.apply({"params": params}, batch["dense"],
                             batch["sparse"])
        loss = optax.sigmoid_binary_cross_entropy(
            logits, batch["label"]).mean()
        acc = jnp.mean((logits > 0) == (batch["label"] > 0.5))
        # corrcoef is NaN when labels (or preds) are constant in the batch
        # (zero std); report 0 correlation instead of poisoning the stream.
        auc_proxy = jnp.nan_to_num(
            jnp.corrcoef(jax.nn.sigmoid(logits), batch["label"])[0, 1])
        return loss, LossAux(extra=extra,
                             metrics={"accuracy": acc,
                                      "pred_corr": auc_proxy})

    return loss_fn
