"""ResNet-20 (CIFAR) and ResNet-50 (ImageNet) — BASELINE configs 2 and 3.

Reference capability replaced: the CIFAR config ran under
``MultiWorkerMirroredStrategy`` + NCCL ring all-reduce (SURVEY.md §3.5); the
ImageNet ResNet-50 row is the north-star metric. Both collapse to the shared
pjit'd train step — the all-reduce is the same mean-gradient XLA collective.

TPU-first choices:
- compute in bfloat16 (MXU-native), params and BN statistics in float32;
- NHWC layout (XLA TPU's preferred conv layout);
- BatchNorm without ``axis_name``: under GSPMD the batch mean over a
  data-sharded batch *is* the global mean (XLA inserts the collective), so
  this is cross-replica sync-BN for free — per-replica BN like the
  reference's is a behavioral delta documented in README.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn

from dtf_tpu.core.train import LossAux

ModuleDef = Any


class BasicBlock(nn.Module):
    """2×3x3 block (ResNet-18/20/34 family)."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides))(x)
        y = nn.relu(self.norm()(y))
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1),
                                 (self.strides, self.strides),
                                 name="shortcut")(residual)
            residual = self.norm(name="shortcut_bn")(residual)
        return nn.relu(y + residual)


class BottleneckBlock(nn.Module):
    """1-3-1 bottleneck (ResNet-50/101/152), v1.5: stride on the 3x3."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = nn.relu(self.norm()(y))
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides))(y)
        y = nn.relu(self.norm()(y))
        y = self.conv(self.filters * 4, (1, 1))(y)
        # zero-init the last BN scale: residual branch starts as identity
        # (standard large-batch trick; matters for the MWMS parity config).
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 (self.strides, self.strides),
                                 name="shortcut")(residual)
            residual = self.norm(name="shortcut_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block: ModuleDef
    num_classes: int
    num_filters: int = 64
    stem: str = "imagenet"  # "imagenet": 7x7/2 + maxpool; "cifar": 3x3/1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, padding="SAME",
                       dtype=self.dtype, param_dtype=jnp.float32)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       param_dtype=jnp.float32)
        x = x.astype(self.dtype)
        if self.stem == "imagenet":
            x = conv(self.num_filters, (7, 7), (2, 2), name="stem_conv")(x)
            x = nn.relu(norm(name="stem_bn")(x))
            x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        else:
            x = conv(self.num_filters, (3, 3), name="stem_conv")(x)
            x = nn.relu(norm(name="stem_bn")(x))
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if (i > 0 and j == 0) else 1
                x = self.block(self.num_filters * 2 ** i, strides,
                               conv=conv, norm=norm)(x)
        x = jnp.mean(x, axis=(1, 2))
        # classifier head in f32 for numerically stable softmax.
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=jnp.float32, name="head")(x)


def resnet20(num_classes: int = 10, dtype=jnp.bfloat16) -> ResNet:
    """CIFAR ResNet-20: 3 stages × 3 basic blocks, 16 base filters."""
    return ResNet(stage_sizes=(3, 3, 3), block=BasicBlock,
                  num_classes=num_classes, num_filters=16, stem="cifar",
                  dtype=dtype)


def resnet50(num_classes: int = 1000, dtype=jnp.bfloat16) -> ResNet:
    """ImageNet ResNet-50 v1.5 — the north-star benchmark model."""
    return ResNet(stage_sizes=(3, 4, 6, 3), block=BottleneckBlock,
                  num_classes=num_classes, num_filters=64, stem="imagenet",
                  dtype=dtype)


def make_init(model: ResNet, image_shape: tuple[int, ...]):
    def init_fn(rng):
        return model.init(rng, jnp.zeros((1, *image_shape), jnp.float32),
                          train=False)

    return init_fn


def make_loss(model: ResNet, *, weight_decay: float = 0.0,
              logits_sharding=None):
    """Cross-entropy (+ optional L2 on kernels) with BN-stat updates.

    ``logits_sharding``: pass a NamedSharding to gather TP-sharded logits
    before the loss (needed when the head is column-sharded over ``model`` —
    the class-dim gather in cross-entropy cannot run on a sharded axis; with
    few classes the all-gather is noise. Large-vocab models use the sharded
    cross-entropy in :mod:`dtf_tpu.ops` instead.)"""

    def loss_fn(params, extra, batch, rng):
        logits, new_vars = model.apply(
            {"params": params, **extra}, batch["image"], train=True,
            mutable=["batch_stats"])
        if logits_sharding is not None:
            logits = jax.lax.with_sharding_constraint(logits, logits_sharding)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"]).mean()
        if weight_decay:
            l2 = sum(jnp.sum(jnp.square(p))
                     for path, p in jax.tree_util.tree_flatten_with_path(
                         params)[0] if path[-1].key == "kernel")
            loss = loss + weight_decay * 0.5 * l2
        acc = jnp.mean(jnp.argmax(logits, -1) == batch["label"])
        return loss, LossAux(extra=dict(new_vars),
                             metrics={"accuracy": acc})

    return loss_fn


def make_eval(model: ResNet):
    def eval_fn(params, extra, batch):
        logits = model.apply({"params": params, **extra}, batch["image"],
                             train=False)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"]).mean()
        acc = jnp.mean(jnp.argmax(logits, -1) == batch["label"])
        return {"eval_loss": loss, "eval_accuracy": acc}

    return eval_fn
