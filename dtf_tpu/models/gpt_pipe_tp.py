"""Megatron tensor parallelism INSIDE pipeline stages (TP x PP x DP).

`gpt_pipe` runs blocks mesh-less inside the pipeline's ``shard_map``, so
``--mesh_model`` idled under ``--mesh_pipe``. This module composes them the
Megatron way: the pipeline body is manual over ('pipe','data','model'), and
each transformer block is written with explicit column-/row-parallel
matmuls — qkv and mlp-in column-sharded (no communication, each shard owns
``heads/tp`` heads), attn-out and mlp-out row-sharded with ONE
``lax.psum`` over ``model`` per residual branch (the Megatron f/g
operators), row biases added once after the psum.

The stage is PURE FUNCTIONS over a param pytree, not flax modules: flax
re-validates declared param shapes at apply time, which can never hold when
params arrive as shard-local slices inside ``shard_map`` (global [d, d] at
init, local [d, d/tp] at apply). Plain functions use runtime shapes —
head counts derive from the local qkv width — so the SAME code serves the
sharded pipeline body (``tp_axis='model'``) and the unsharded sequential
parity oracle (``tp_axis=None``); init always runs global, outside the
mesh, with no collectives traced.

Reference: TP and PP are both beyond the reference's scope (SURVEY.md §2c);
this is the composition a real TPU framework needs for models that exceed
one chip under either axis alone.
"""

from __future__ import annotations

import re
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh, PartitionSpec as P

from dtf_tpu.core.sharding import path_str
from dtf_tpu.core.train import LossAux
from dtf_tpu.models.gpt import GPTConfig, rope
from dtf_tpu.models.gpt_pipe import GPTEmbed, GPTHead, validate_pipe_cfg
from dtf_tpu.ops import attention as att
from dtf_tpu.ops.losses import softmax_cross_entropy
from dtf_tpu.parallel import pipeline as pp

PyTree = Any


# ------------------------------------------------------------------ params

def _init_dense(rng, d_in: int, d_out: int) -> PyTree:
    return {"kernel": nn.initializers.lecun_normal()(rng, (d_in, d_out),
                                                     jnp.float32),
            "bias": jnp.zeros((d_out,), jnp.float32)}


def _init_ln(d: int) -> PyTree:
    return {"scale": jnp.ones((d,), jnp.float32),
            "bias": jnp.zeros((d,), jnp.float32)}


def init_block(rng: jax.Array, cfg: GPTConfig) -> PyTree:
    ks = jax.random.split(rng, 6)
    d, dff = cfg.d_model, cfg.d_ff
    return {
        "ln1": _init_ln(d),
        "query": _init_dense(ks[0], d, d),
        "key": _init_dense(ks[1], d, d),
        "value": _init_dense(ks[2], d, d),
        "attn_out": _init_dense(ks[3], d, d),
        "ln2": _init_ln(d),
        "mlp_in": _init_dense(ks[4], d, dff),
        "mlp_out": _init_dense(ks[5], dff, d),
    }


def init_stage(rng: jax.Array, cfg: GPTConfig, n_layers: int) -> PyTree:
    return {f"block_{i}": init_block(k, cfg)
            for i, k in enumerate(jax.random.split(rng, n_layers))}


# ------------------------------------------------------------------- apply

def _layernorm(x, p):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + 1e-6)
    return (y * p["scale"] + p["bias"]).astype(x.dtype)


def _col(p, x, dtype):
    """Column-parallel matmul: local kernel [d_in, d_out/tp]; bias is the
    matching local slice; output is this shard's columns. No comm."""
    return x @ p["kernel"].astype(dtype) + p["bias"].astype(dtype)


def _row(p, x, dtype, tp_axis):
    """Row-parallel matmul: local kernel [d_in/tp, d_out] makes a partial
    product; ONE psum reduces over tp; the replicated bias is added once,
    after the reduction (Megatron's g operator)."""
    y = x @ p["kernel"].astype(dtype)
    if tp_axis is not None:
        y = jax.lax.psum(y, tp_axis)
    return y + p["bias"].astype(dtype)


def apply_block(cfg: GPTConfig, tp_axis: Optional[str], p: PyTree,
                x: jax.Array) -> jax.Array:
    d_head = cfg.d_model // cfg.heads
    b, t, _ = x.shape
    dtype = cfg.dtype
    x = x.astype(dtype)

    h = _layernorm(x, p["ln1"])

    def split(v):  # [B,T,local_width] -> [B,local_heads,T,d_head]
        return v.reshape(b, t, -1, d_head).transpose(0, 2, 1, 3)

    q = split(_col(p["query"], h, dtype))
    k = split(_col(p["key"], h, dtype))
    v = split(_col(p["value"], h, dtype))
    positions = jnp.arange(t)
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)
    out = att.dense_attention(q, k, v, causal=True, window=cfg.attn_window)
    out = out.transpose(0, 2, 1, 3).reshape(b, t, -1)
    x = x + _row(p["attn_out"], out, dtype, tp_axis)

    h = _layernorm(x, p["ln2"])
    y = nn.gelu(_col(p["mlp_in"], h, dtype), approximate=True)
    y = _row(p["mlp_out"], y, dtype, tp_axis)
    return x + y


def apply_stage(cfg: GPTConfig, tp_axis: Optional[str], n_layers: int,
                p: PyTree, x: jax.Array) -> jax.Array:
    fn = apply_block
    if cfg.remat:
        fn = jax.checkpoint(apply_block, static_argnums=(0, 1))
    for i in range(n_layers):
        x = fn(cfg, tp_axis, p[f"block_{i}"], x)
    return x


# ---------------------------------------------------------------- sharding

def _stage_spec_for(path: str, pipe_axis: str, tp_axis: str) -> P:
    """Per-leaf PartitionSpec for a STACKED stage tree (leading row dim)."""
    if re.search(r"(query|key|value|mlp_in)/kernel", path):
        return P(pipe_axis, None, tp_axis)       # column parallel
    if re.search(r"(query|key|value|mlp_in)/bias", path):
        return P(pipe_axis, tp_axis)
    if re.search(r"(attn_out|mlp_out)/kernel", path):
        return P(pipe_axis, tp_axis, None)       # row parallel
    return P(pipe_axis)                          # LN params, row biases


def stage_specs(stacked_params: PyTree, *, pipe_axis: str = "pipe",
                tp_axis: str = "model") -> PyTree:
    return jax.tree_util.tree_map_with_path(
        lambda p, _: _stage_spec_for(path_str(p), pipe_axis, tp_axis),
        stacked_params)


def pipe_tp_rules(pipe_axis: str = "pipe", tp_axis: str = "model"):
    """create_train_state param_rules for the full {embed,stages,head} tree."""
    return [
        (r"stages/.*(query|key|value|mlp_in)/kernel",
         P(pipe_axis, None, tp_axis)),
        (r"stages/.*(query|key|value|mlp_in)/bias", P(pipe_axis, tp_axis)),
        (r"stages/.*(attn_out|mlp_out)/kernel", P(pipe_axis, tp_axis, None)),
        (r"^stages/", P(pipe_axis)),
    ]


# --------------------------------------------------------------- factories

def _check(cfg: GPTConfig, mesh: Mesh, axis_name: str, tp_axis: str) -> int:
    n_stages = mesh.shape.get(axis_name, 1)
    per_row = validate_pipe_cfg(cfg, n_stages, 1)
    tp = mesh.shape.get(tp_axis, 1)
    if cfg.heads % tp:
        raise ValueError(f"{cfg.heads} heads not divisible by {tp_axis}={tp}")
    if cfg.d_ff % tp or cfg.d_model % tp:
        raise ValueError(
            f"d_model={cfg.d_model}/d_ff={cfg.d_ff} not divisible by "
            f"{tp_axis}={tp}")
    if cfg.attn_impl not in ("dense", "auto"):
        raise ValueError(
            f"TP-in-pipe blocks use per-shard dense attention; "
            f"attn_impl={cfg.attn_impl!r} is not supported here")
    if cfg.kv_heads is not None and cfg.kv_heads != cfg.heads:
        # this path builds its own full-width K/V params; accepting a GQA
        # config would silently train plain MHA under a GQA label
        raise ValueError(
            "grouped-query attention (kv_heads) is not supported in the "
            "TP-in-pipe path; use the plain or pipeline-only GPT")
    return per_row


def make_pipe_tp_init(cfg: GPTConfig, mesh: Mesh, *, seq_len: int = 128,
                      axis_name: str = "pipe", tp_axis: str = "model"):
    per_row = _check(cfg, mesh, axis_name, tp_axis)
    n_stages = mesh.shape.get(axis_name, 1)
    b = mesh.shape.get("data", 1)

    def init_fn(rng):
        r_e, r_s, r_h = jax.random.split(rng, 3)
        ids = jnp.zeros((b, seq_len), jnp.int32)
        x = jnp.zeros((1, seq_len, cfg.d_model), cfg.dtype)
        return {"params": {
            "embed": GPTEmbed(cfg).init(r_e, ids)["params"],
            "stages": pp.init_stacked(
                lambda r: init_stage(r, cfg, per_row), n_stages, r_s),
            "head": GPTHead(cfg).init(r_h, x)["params"],
        }}

    return init_fn


def make_pipe_tp_loss(cfg: GPTConfig, mesh: Mesh, *, n_microbatches: int,
                      axis_name: str = "pipe", tp_axis: str = "model"):
    """Loss fn: GPipe schedule over ``pipe`` with Megatron TP over
    ``tp_axis`` inside every stage."""
    per_row = _check(cfg, mesh, axis_name, tp_axis)

    def stage_fn(stage_params, x):
        return apply_stage(cfg, tp_axis, per_row, stage_params, x)

    pipe = pp.pipeline_spmd(
        stage_fn, n_microbatches, mesh, axis_name=axis_name,
        param_specs_fn=lambda params: stage_specs(
            params, pipe_axis=axis_name, tp_axis=tp_axis),
        check_vma=False)

    def loss_fn(params, extra, batch, rng):
        del rng
        x = GPTEmbed(cfg).apply({"params": params["embed"]},
                                batch["input_ids"])
        x = pipe(params["stages"], x)
        logits = GPTHead(cfg).apply({"params": params["head"]}, x)
        loss, n = softmax_cross_entropy(logits, batch["labels"],
                                        ignore_index=-100)
        return loss, LossAux(extra=extra, metrics={"lm_tokens": n}, weight=n)

    return loss_fn


def make_pipe_tp_eval(cfg: GPTConfig, n_stages: int):
    """Held-out eval for the TP-in-pipe layout (VERDICT r3 #7): stages
    applied sequentially with ``tp_axis=None`` on the stacked params —
    identical math to :func:`make_sequential_tp_loss`; GSPMD moves the
    P('pipe', …, 'model') rows as needed (eval is off the critical path)."""
    per_row = validate_pipe_cfg(cfg, n_stages, 1)

    def eval_fn(params, extra, batch):
        del extra
        p = params["params"] if "params" in params else params
        x = GPTEmbed(cfg).apply({"params": p["embed"]}, batch["input_ids"])
        for s in range(n_stages):
            row = jax.tree.map(lambda t: t[s], p["stages"])
            x = apply_stage(cfg, None, per_row, row, x)
        logits = GPTHead(cfg).apply({"params": p["head"]}, x)
        loss, _ = softmax_cross_entropy(logits, batch["labels"],
                                        ignore_index=-100)
        return {"eval_loss": loss, "eval_ppl": jnp.exp(loss)}

    return eval_fn


def make_sequential_tp_loss(cfg: GPTConfig, n_stages: int):
    """Parity oracle: the same block functions with ``tp_axis=None`` on the
    full params, stages applied in order — identical math, no mesh."""
    per_row = validate_pipe_cfg(cfg, n_stages, 1)

    def loss_fn(params, extra, batch, rng):
        del rng
        x = GPTEmbed(cfg).apply({"params": params["embed"]},
                                batch["input_ids"])
        for s in range(n_stages):
            row = jax.tree.map(lambda t: t[s], params["stages"])
            x = apply_stage(cfg, None, per_row, row, x)
        logits = GPTHead(cfg).apply({"params": params["head"]}, x)
        loss, n = softmax_cross_entropy(logits, batch["labels"],
                                        ignore_index=-100)
        return loss, LossAux(extra=extra, metrics={"lm_tokens": n}, weight=n)

    return loss_fn
