"""Metrics / observability — successor of SummarySaverHook + LoggingTensorHook.

Reference capability replaced (SURVEY.md §5.5): scalar loss/accuracy to
TensorBoard via ``tf.summary.FileWriter`` (chief only) and stdout step logs.
Here: ``clu.metric_writers`` (TensorBoard summaries + logging), written only
by process 0, plus host-side logging from inside jit via
``jax.debug.callback`` (the supported successor of the removed
``jax.experimental.host_callback`` named in the north star).
"""

from __future__ import annotations

import logging
from typing import Mapping

# jax is imported lazily inside the two call sites that need it:
# dtf_tpu.telemetry's span/flight modules import `quantile` from here, and
# the telemetry package must import on machines with no backend at all
# (the srclint lazy-import fence + tests/test_analysis.py no-backend test).

log = logging.getLogger("dtf_tpu")


def quantile(xs, q):
    """Nearest-rank quantile of a small sample (None when empty) — the one
    shared implementation behind the serve scheduler's TTFT p50/p99 and
    telemetry's per-phase rollups, so every report quotes the same
    convention."""
    if not xs:
        return None
    xs = sorted(xs)
    i = min(len(xs) - 1, max(0, round(q * (len(xs) - 1))))
    return xs[i]


class MetricWriter:
    """Scalar writer: stdout logging always, TensorBoard when logdir given."""

    def __init__(self, logdir: str | None = None, *, also_log: bool = True):
        import jax

        self._writers = []
        self._is_chief = jax.process_index() == 0
        if not self._is_chief:
            return
        if also_log:
            from clu.metric_writers import LoggingWriter

            self._writers.append(LoggingWriter())
        if logdir:
            try:
                from clu.metric_writers import SummaryWriter

                self._writers.append(SummaryWriter(logdir))
            except Exception as e:  # pragma: no cover - env-dependent (TF)
                log.warning("TensorBoard summary writer unavailable: %s", e)

    def write_scalars(self, step: int, scalars: Mapping[str, float]) -> None:
        if not self._writers:
            return
        scalars = {k: float(v) for k, v in scalars.items()}
        for w in self._writers:
            w.write_scalars(int(step), scalars)

    def flush(self) -> None:
        for w in self._writers:
            w.flush()

    def close(self) -> None:
        for w in self._writers:
            w.close()


def jit_log(fmt: str, **values) -> None:
    """Log scalars from inside a jitted function (host callback).

    Usage inside a loss/step function: ``jit_log("loss={loss}", loss=loss)``.
    Unlike the reference's ``LoggingTensorHook`` (which ran a separate fetch
    through the session), this rides the compiled program asynchronously.
    """

    import jax

    def _cb(**kw):
        log.info(fmt.format(**{k: float(v) for k, v in kw.items()}))

    jax.debug.callback(_cb, **values)
