"""Checkpoint/resume — successor of Saver + CheckpointSaverHook + restore.

Reference capability replaced (SURVEY.md §5.4): ``tf.train.Saver`` driven by
``CheckpointSaverHook`` on the chief (save every N steps/secs to ``--logdir``),
with automatic restore-if-exists in ``ChiefSessionCreator``. There, variables
lived on parameter servers, so the chief pulled every tensor over gRPC to
write one file. Here state is GSPMD-sharded and Orbax writes each shard from
the process that owns it, asynchronously — no gather, no traffic spike.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any

import jax
import orbax.checkpoint as ocp

PyTree = Any

log = logging.getLogger("dtf_tpu")

#: the model-config manifest written next to the Orbax step dirs by the
#: training launchers (currently train_gpt.py) and auto-loaded by the
#: serving entrypoints — see save_model_config / load_model_config.
MODEL_CONFIG_BASENAME = "model_config.json"


def save_model_config(directory: str | os.PathLike, config: dict) -> None:
    """Write the architecture manifest next to the checkpoint (chief only).

    The serving entrypoints (``generate_gpt.py`` / ``serve_gpt.py``) decode
    with whatever config they are handed; before this manifest existed they
    trusted hand-matched ``--size``-style flags, and a mismatch silently
    garbled decode (wrong head count reads the cache at the wrong stride —
    no shape error). Training launchers call this once at startup; values
    must be JSON-serializable.
    """
    if jax.process_index() != 0:
        return
    directory = os.fspath(directory)
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, MODEL_CONFIG_BASENAME)
    with open(path, "w") as f:
        json.dump(config, f, indent=1, sort_keys=True)
        f.write("\n")


def load_model_config(directory: str | os.PathLike) -> dict | None:
    """The manifest saved by :func:`save_model_config`, or None (old
    checkpoints / corrupt file — callers fall back to flags, loudly)."""
    path = os.path.join(os.fspath(directory), MODEL_CONFIG_BASENAME)
    try:
        with open(path) as f:
            return json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, json.JSONDecodeError) as e:
        log.warning("unreadable %s (%s); falling back to flags", path, e)
        return None


#: substrings Orbax puts in TARGET-mismatch errors (the restore tree's
#: SHAPE vs what was saved — e.g. "Dict key mismatch; expected keys:
#: [...]", "User-provided restore item and on-disk value mismatch").
#: Deliberately narrow: corruption can surface as a tensorstore
#: "checksum mismatch", which must stay on the fall-back path — so plain
#: "mismatch" is not enough of a signature. Unknown error classes keep
#: the old fall-back behavior; only unambiguous wrong-target phrasings
#: re-raise. Verified against both classes in tests/test_elastic.py.
_STRUCTURAL_ERROR_MARKERS = ("key mismatch", "user-provided restore item",
                             "tree structure")


def _looks_structural(e: Exception) -> bool:
    return any(m in str(e).lower() for m in _STRUCTURAL_ERROR_MARKERS)


class Checkpointer:
    """Thin Orbax CheckpointManager wrapper for TrainState pytrees."""

    def __init__(self, directory: str | os.PathLike, *, max_to_keep: int = 3,
                 save_interval_steps: int = 1, async_save: bool = True,
                 wall=time.time):
        directory = os.fspath(directory)
        os.makedirs(directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=async_save,
            ),
        )
        #: the step the last guarded latest-step restore actually loaded
        #: (may be OLDER than latest when the newest step was unreadable)
        self._last_restored_step: int | None = None
        #: extra-item providers: ``{name: fn(step) -> JSON-able value}``,
        #: folded into every :meth:`save` next to the state/params items
        #: (the streaming tier registers ``stream`` here so the SIGTERM
        #: ``save_durable`` path cannot forget the stream state).
        self._extra_providers: dict = {}
        #: injectable wall clock stamping :attr:`resume_events` (tests
        #: pin it; the host pass's clock-escape discipline).
        self._wall = wall
        #: structured degraded-resume records (missing/unreadable extra
        #: items) — the WARN paths leave a machine-readable trail here so
        #: launchers can fold "what did this resume silently drop" into
        #: their run reports instead of grepping logs.
        self.resume_events: list[dict] = []
        #: optional fleet EventLog (ISSUE 20) — save/restore/fallback
        #: verdicts land on the run timeline too.
        self._event_log = None

    def attach_event_log(self, event_log) -> None:
        """Mirror checkpoint lifecycle (saves queued, guarded-restore
        fallbacks, degraded resumes) onto a fleet
        :class:`dtf_tpu.telemetry.events.EventLog`."""
        self._event_log = event_log

    def _ckpt_event(self, kind: str, /, **fields) -> None:
        if self._event_log is not None:
            self._event_log.emit(kind, directory=self.directory, **fields)

    @property
    def directory(self) -> str:
        return os.fspath(self._mgr.directory)

    @property
    def last_restored_step(self) -> int | None:
        """The step the last guarded latest-step restore
        (:meth:`restore`/:meth:`restore_params` with ``step=None``)
        actually loaded — part of the fallback contract: it may be OLDER
        than :meth:`latest_step` when the newest step was unreadable, and
        callers reporting "what am I serving/resuming" must report this,
        not latest. None before any guarded restore."""
        return self._last_restored_step

    def add_extra_provider(self, name: str, fn) -> None:
        """Register ``fn(step) -> JSON-able value`` as a standing extra
        item: every subsequent :meth:`save`/:meth:`save_durable` includes
        its value for the step being saved (provider registration beats
        threading an ``extra_items`` through every save call site — the
        preemption path especially must not be forgettable)."""
        if name in ("state", "params"):
            raise ValueError(f"extra item name {name!r} is reserved")
        self._extra_providers[name] = fn

    def _extra_args(self, step: int, extra_items: dict | None) -> dict:
        items = {name: fn(step) for name, fn in self._extra_providers.items()}
        if extra_items:
            for name in extra_items:
                if name in ("state", "params"):
                    raise ValueError(
                        f"extra item name {name!r} is reserved")
            items.update(extra_items)
        return {name: ocp.args.JsonSave(value)
                for name, value in items.items()}

    def save(self, step: int, state: PyTree, *, force: bool = False,
             extra_items: dict | None = None) -> bool:
        """Async sharded save. Returns True if a save was actually queued.

        When ``state`` carries a params subtree (TrainState attribute or
        dict key), it is ALSO saved as a separate ``params`` item next to
        the full ``state`` item, so a serving process can restore just the
        weights instead of reading ~3x params bytes of dead opt_state
        (:meth:`restore_params`). Anything else keeps the legacy
        single-item layout.

        ``extra_items`` — ``{name: JSON-able value}`` saved as additional
        Composite members next to the state (merged over the registered
        :meth:`add_extra_provider` values); read back by
        :meth:`restore_extra`, which treats their absence in an older
        checkpoint as a WARN, never a raise. The streaming data tier's
        ``stream`` StreamState is the motivating member (docs/DATA.md).

        Deliberate cost: the params bytes are stored twice (~25% more per
        Adam checkpoint). The alternative — state-minus-params plus
        reassembly on every restore path — would complicate
        restore/restore_raw/preemption-resume for a storage win that
        ``max_to_keep`` already bounds; revisit if checkpoints outgrow it.
        """
        step = int(step)
        if step in self._mgr.all_steps():
            return False
        extras = self._extra_args(step, extra_items)
        params = getattr(state, "params", None)
        if params is None and isinstance(state, dict):
            params = state.get("params")
        if params is None:
            if not extras:
                queued = self._mgr.save(
                    step, args=ocp.args.StandardSave(state), force=force)
            else:
                queued = self._mgr.save(
                    step, args=ocp.args.Composite(
                        state=ocp.args.StandardSave(state), **extras),
                    force=force)
        else:
            queued = self._mgr.save(
                step,
                args=ocp.args.Composite(state=ocp.args.StandardSave(state),
                                        params=ocp.args.StandardSave(params),
                                        **extras),
                force=force)
        if queued:
            self._ckpt_event("ckpt_save", step=step)
        return queued

    def save_params(self, step: int, params: PyTree, *,
                    force: bool = True) -> bool:
        """Save a PARAMS-ONLY step: just the ``params`` item, no ``state``
        twin — the weight-publish path (:mod:`dtf_tpu.publish`), where
        ``step`` is a publish VERSION and the tree is weights by
        definition. :meth:`restore_params` reads it back (``_has_item``
        routes by the ``params`` subdir), and the guarded latest-step walk
        covers these steps exactly like training checkpoints."""
        step = int(step)
        if step in self._mgr.all_steps():
            return False
        return self._mgr.save(
            step, args=ocp.args.Composite(params=ocp.args.StandardSave(params)),
            force=force)

    def save_durable(self, step: int, state: PyTree, *, retries: int = 2,
                     backoff_s: float = 0.25, sleep=None,
                     extra_items: dict | None = None) -> bool:
        """Force-save ``step`` and block until durable, retrying transient
        failures with exponential backoff.

        The PreemptionHook path: a save failing inside the SIGTERM grace
        window (filesystem blip, transient quota) must not forfeit the
        whole window — retry ``retries`` times, and if every attempt
        fails, log the error and return False so the caller can still exit
        cleanly on the PREVIOUS checkpoint (Orbax writes are atomic: a
        failed attempt leaves no half-step behind for restore to trip on).
        """
        sleep = sleep or time.sleep
        for attempt in range(retries + 1):
            try:
                self.save(step, state, force=True, extra_items=extra_items)
                self.wait()
                return True
            except Exception as e:  # noqa: BLE001 — any failure class
                # here must degrade to "previous checkpoint", not a crash
                try:
                    self._mgr.wait_until_finished()
                except Exception:   # noqa: BLE001 — the failed async
                    pass            # save's own error re-raised; drained
                if attempt == retries:
                    log.error(
                        "checkpoint save at step %d failed after %d "
                        "attempt(s) (%s: %s); the previous checkpoint "
                        "(step %s) remains the resume point",
                        step, retries + 1, type(e).__name__, e,
                        self._mgr.latest_step())
                    return False
                delay = backoff_s * (2 ** attempt)
                log.warning(
                    "checkpoint save at step %d failed (%s: %s); "
                    "retrying in %.2fs (%d/%d)",
                    step, type(e).__name__, e, delay, attempt + 1, retries)
                sleep(delay)
        return False

    def _has_item(self, step: int, item: str) -> bool:
        """True when ``step`` was saved in the two-item layout and carries
        ``item`` (legacy checkpoints keep everything under ``default``)."""
        return os.path.isdir(os.path.join(self.directory, str(step), item))

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def _restore_one(self, target: PyTree, step: int) -> PyTree:
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=x.sharding)
            if isinstance(x, jax.Array) else x, target)
        if self._has_item(step, "state"):
            return self._mgr.restore(
                step, args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(abstract)))["state"]
        return self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))

    def restore(self, target: PyTree, step: int | None = None) -> PyTree:
        """Restore into the shardings of ``target``.

        ``target`` may be a concrete sharded TrainState (its leaves' shardings
        are reused — the restore-if-exists moment of ``ChiefSessionCreator``)
        or a pytree of ShapeDtypeStruct with shardings. The shardings may
        belong to a DIFFERENT mesh than the one that saved: Orbax reshards
        on read, which is the whole elastic-resume story
        (``fault/elastic.py``, docs/RESILIENCE.md).

        With ``step=None`` (the relaunch path) a corrupt/truncated newest
        checkpoint is not fatal: restore WARNs and falls back to the next
        older step, crashing only when every step on disk is unreadable.
        An explicitly requested step gets no fallback — the caller asked
        for exactly that step.
        """
        if step is not None:
            return self._restore_one(target, step)
        steps = sorted(self._mgr.all_steps(), reverse=True)
        if not steps:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}")
        last_err: Exception | None = None
        for i, s in enumerate(steps):
            try:
                restored = self._restore_one(target, s)
            except Exception as e:  # noqa: BLE001 — ANY unreadable-step
                # class (truncated arrays, garbage metadata, missing
                # files) must fall back, not crash the relaunch
                if _looks_structural(e):
                    # a WRONG RESTORE TARGET (tree-structure mismatch: the
                    # relaunch built state for a different model config)
                    # would fail identically against every step — falling
                    # back would bury the misconfiguration under a bogus
                    # "all checkpoints corrupt" story. Re-raise it as
                    # itself, immediately.
                    raise
                last_err = e
                older = steps[i + 1] if i + 1 < len(steps) else None
                log.warning(
                    "checkpoint step %d at %s is unreadable (%s: %.200s); "
                    "falling back to %s", s, self.directory,
                    type(e).__name__, e,
                    f"step {older}" if older is not None
                    else "nothing — no older step")
                self._ckpt_event("ckpt_fallback", bad_step=s,
                                 error=type(e).__name__)
                continue
            if s != steps[0]:
                log.warning(
                    "resumed from step %d instead of the newest step %d "
                    "(unreadable); training will redo the difference", s,
                    steps[0])
            self._last_restored_step = s
            self._ckpt_event("ckpt_restore", step=s, newest=steps[0])
            return restored
        raise RuntimeError(
            f"every checkpoint step under {self.directory} is unreadable "
            f"(tried {steps}) — corrupt files, or a restore target whose "
            f"mismatch this guard didn't recognize; last error: "
            f"{type(last_err).__name__}: {last_err}")

    def restore_raw(self, step: int | None = None) -> PyTree:
        """Restore exactly as saved, no target tree required.

        StandardSave'd pytrees come back as nested dicts — a saved
        TrainState yields keys ``params`` / ``opt_state`` / ``step`` /
        ``extra`` / ``rng``.

        Known cost: the FULL saved tree is read (opt-state included, ~3x
        params bytes for Adam) — Orbax's Standard handler pairs only with
        StandardRestore and has no partial-subtree restore
        (PyTreeRestore(partial_restore=True) raises a handler-mismatch
        ValueError against StandardSave'd checkpoints). Serving should use
        :meth:`restore_params`, which reads the separate ``params`` item
        new saves write and pays this cost only on legacy checkpoints.
        """
        step = self._mgr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}")
        if self._has_item(step, "state"):
            return self._mgr.restore(
                step, args=ocp.args.Composite(
                    state=ocp.args.StandardRestore()))["state"]
        try:
            return self._mgr.restore(step)
        except KeyError:
            # a manager that has not saved this session cannot infer the
            # legacy single-item handler — name it explicitly
            return self._mgr.restore(step,
                                     args=ocp.args.StandardRestore())

    def _restore_params_one(self, step: int) -> PyTree:
        if self._has_item(step, "params"):
            return self._mgr.restore(
                step, args=ocp.args.Composite(
                    params=ocp.args.StandardRestore()))["params"]
        log.warning(
            "step %d at %s predates the params-only item; falling back to "
            "the full-tree restore (~3x params bytes of dead opt_state)",
            step, self.directory)
        raw = self.restore_raw(step)
        if not isinstance(raw, dict) or "params" not in raw:
            raise ValueError(
                f"checkpoint step {step} at {self.directory} has no "
                "'params' subtree — not a TrainState checkpoint?")
        return raw["params"]

    def restore_params(self, step: int | None = None) -> PyTree:
        """Params-only restore — the serving startup entry.

        New checkpoints carry a dedicated ``params`` item (see
        :meth:`save`): only the weight bytes are read. Legacy single-item
        checkpoints fall back to :meth:`restore_raw` (full-tree read,
        opt_state included) with a warning, so old logdirs keep serving.

        With ``step=None`` this rides the same guarded latest-step walk as
        :meth:`restore` (ISSUE 12 parity): a corrupt/truncated newest
        checkpoint WARNs and serves the next older readable step instead
        of killing serving startup outright. Unambiguous WRONG-TARGET
        errors (tree mismatch / not a TrainState checkpoint) still
        re-raise immediately, and an explicitly requested step gets no
        fallback — the caller asked for exactly that step.
        """
        if step is not None:
            return self._restore_params_one(step)
        steps = sorted(self._mgr.all_steps(), reverse=True)
        if not steps:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}")
        last_err: Exception | None = None
        for i, s in enumerate(steps):
            try:
                params = self._restore_params_one(s)
            except Exception as e:  # noqa: BLE001 — any unreadable-step
                # class must fall back (restore()'s contract); only the
                # unambiguous wrong-target phrasings re-raise
                if _looks_structural(e) or "'params' subtree" in str(e):
                    raise
                last_err = e
                older = steps[i + 1] if i + 1 < len(steps) else None
                log.warning(
                    "checkpoint step %d at %s is unreadable (%s: %.200s); "
                    "falling back to %s", s, self.directory,
                    type(e).__name__, e,
                    f"step {older}" if older is not None
                    else "nothing — no older step")
                continue
            if s != steps[0]:
                log.warning(
                    "serving params of step %d instead of the newest step "
                    "%d (unreadable)", s, steps[0])
            self._last_restored_step = s
            return params
        raise RuntimeError(
            f"every checkpoint step under {self.directory} is unreadable "
            f"(tried {steps}) — corrupt files, or a restore failure this "
            f"guard didn't recognize; last error: "
            f"{type(last_err).__name__}: {last_err}")

    def restore_extra(self, name: str, step: int | None = None):
        """One extra Composite item (see :meth:`save` ``extra_items``), or
        None — with a WARN — when ``step`` predates the item (a legacy
        checkpoint must restore WITHOUT its stream state, not raise: the
        model state is intact, and the stream can rebuild from its spec).
        ``step=None`` reads the step the last guarded restore loaded (the
        consistent pair for restore-if-exists), falling back to latest.
        """
        if step is None:
            step = (self._last_restored_step
                    if self._last_restored_step is not None
                    else self._mgr.latest_step())
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}")
        step = int(step)
        if not self._has_item(step, name):
            log.warning(
                "checkpoint step %d at %s has no %r item (saved before "
                "this extra existed); restoring without it", step,
                self.directory, name)
            self.resume_events.append({
                "event": "missing-extra", "item": name, "step": step,
                "t": round(self._wall(), 3)})
            self._ckpt_event("ckpt_resume_degraded", kind="missing-extra",
                             item=name, step=step)
            return None
        try:
            return self._mgr.restore(
                step, args=ocp.args.Composite(
                    **{name: ocp.args.JsonRestore()}))[name]
        except Exception as e:  # noqa: BLE001 — an unreadable extra must
            # not take down a restore whose model state is fine
            log.warning(
                "checkpoint step %d at %s: extra item %r is unreadable "
                "(%s: %.200s); restoring without it", step, self.directory,
                name, type(e).__name__, e)
            self.resume_events.append({
                "event": "unreadable-extra", "item": name, "step": step,
                "error": f"{type(e).__name__}: {str(e)[:200]}",
                "t": round(self._wall(), 3)})
            self._ckpt_event("ckpt_resume_degraded", kind="unreadable-extra",
                             item=name, step=step)
            return None

    def restore_if_exists(self, target: PyTree) -> tuple[PyTree, int | None]:
        """(state, restored_step) — state unchanged if nothing on disk.

        Rides :meth:`restore`'s guarded latest-step path: a corrupt newest
        checkpoint falls back to an older readable step (WARN), and
        ``restored_step`` reports the step actually loaded.
        """
        if self._mgr.latest_step() is None:
            return target, None
        restored = self.restore(target, None)
        return restored, self._last_restored_step

    def wait(self) -> None:
        """Block until pending async saves are durable."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
