"""Checkpoint/resume — successor of Saver + CheckpointSaverHook + restore.

Reference capability replaced (SURVEY.md §5.4): ``tf.train.Saver`` driven by
``CheckpointSaverHook`` on the chief (save every N steps/secs to ``--logdir``),
with automatic restore-if-exists in ``ChiefSessionCreator``. There, variables
lived on parameter servers, so the chief pulled every tensor over gRPC to
write one file. Here state is GSPMD-sharded and Orbax writes each shard from
the process that owns it, asynchronously — no gather, no traffic spike.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import orbax.checkpoint as ocp

PyTree = Any


class Checkpointer:
    """Thin Orbax CheckpointManager wrapper for TrainState pytrees."""

    def __init__(self, directory: str | os.PathLike, *, max_to_keep: int = 3,
                 save_interval_steps: int = 1, async_save: bool = True):
        directory = os.fspath(directory)
        os.makedirs(directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=async_save,
            ),
        )

    @property
    def directory(self) -> str:
        return os.fspath(self._mgr.directory)

    def save(self, step: int, state: PyTree, *, force: bool = False) -> bool:
        """Async sharded save. Returns True if a save was actually queued."""
        step = int(step)
        if step in self._mgr.all_steps():
            return False
        return self._mgr.save(step, args=ocp.args.StandardSave(state),
                              force=force)

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, target: PyTree, step: int | None = None) -> PyTree:
        """Restore into the shardings of ``target``.

        ``target`` may be a concrete sharded TrainState (its leaves' shardings
        are reused — the restore-if-exists moment of ``ChiefSessionCreator``)
        or a pytree of ShapeDtypeStruct with shardings.
        """
        step = self._mgr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}")
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=x.sharding)
            if isinstance(x, jax.Array) else x, target)
        return self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))

    def restore_raw(self, step: int | None = None) -> PyTree:
        """Restore exactly as saved, no target tree required.

        The serving-side entry: a decode process wants the params out of a
        training checkpoint without reconstructing the optimizer (whose
        state shapes it can't know). StandardSave'd pytrees come back as
        nested dicts — a saved TrainState yields keys ``params`` /
        ``opt_state`` / ``step`` / ``extra`` / ``rng``.

        Known cost: the FULL saved tree is read (opt-state included, ~3x
        params bytes for Adam) — Orbax's Standard handler, which our saves
        use, pairs only with StandardRestore and has no partial-subtree
        restore (PyTreeRestore(partial_restore=True) raises a
        handler-mismatch ValueError against StandardSave'd checkpoints).
        A one-time startup cost for a serving process; revisit if Orbax
        grows partial StandardRestore.
        """
        step = self._mgr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}")
        return self._mgr.restore(step)

    def restore_if_exists(self, target: PyTree) -> tuple[PyTree, int | None]:
        """(state, restored_step) — state unchanged if nothing on disk."""
        step = self._mgr.latest_step()
        if step is None:
            return target, None
        return self.restore(target, step), step

    def wait(self) -> None:
        """Block until pending async saves are durable."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
