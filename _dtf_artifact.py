"""Bounded-history JSON artifact plumbing shared by the bench parents.

bench_telemetry.py / bench_profile.py merge rows into committed
``{"runs": [...]}`` artifacts (TELEMETRY.json, DEVICE_PROFILE.json) and
fence new rows against the newest committed same-config baseline. Their
parents must NEVER import anything under dtf_tpu (importing the package
pulls jax, which can hang against a dead axon tunnel — the
_dtf_watchdog contract), so the shared helpers live here at the repo
root, importable with no dependencies at all.
"""

from __future__ import annotations

import importlib.util
import json
import os


def _hostio():
    """Load ``dtf_tpu/_hostio.py`` by file location — executing ONLY that
    stdlib-only module, never ``dtf_tpu/__init__`` (which pulls jax and
    can hang against a dead axon tunnel). One atomic-replace
    implementation for the whole repo, without breaking the parents'
    never-import-dtf_tpu contract."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "dtf_tpu", "_hostio.py")
    spec = importlib.util.spec_from_file_location("_dtf_hostio", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def load_runs(path: str) -> list:
    """The artifact's runs list; [] for a missing/malformed file (the
    artifact reader must not be able to fail the bench reporting on it)."""
    try:
        with open(path) as f:
            prev = json.load(f)
        if isinstance(prev, dict) and isinstance(prev.get("runs"), list):
            return prev["runs"]
    except (OSError, ValueError):
        pass
    return []


def merge_runs(path: str, entry: dict, meta: dict,
               keep_runs: int = 20) -> dict:
    """Append one row (newest LAST, history bounded) and rewrite the
    artifact — telemetry.run.merge_artifact's semantics, jax-free."""
    data = {"runs": load_runs(path)}
    data["runs"] = (data["runs"] + [{**entry, **meta}])[-keep_runs:]
    # atomic replace via the repo's one choke point: the sentinel's
    # pathspec commits and concurrent report readers race these merges
    _hostio().atomic_replace(path, json.dumps(data, indent=1))
    return data


def same_config(a: dict, b: dict, keys) -> bool:
    """Rows are fence-comparable only when every identity key matches —
    rows measured under different shapes/models/backends never are."""
    return all(a.get(k) == b.get(k) for k in keys)
