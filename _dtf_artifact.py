"""Bounded-history JSON artifact plumbing shared by the bench parents.

bench_telemetry.py / bench_profile.py merge rows into committed
``{"runs": [...]}`` artifacts (TELEMETRY.json, DEVICE_PROFILE.json) and
fence new rows against the newest committed same-config baseline. Their
parents must NEVER import anything under dtf_tpu (importing the package
pulls jax, which can hang against a dead axon tunnel — the
_dtf_watchdog contract), so the shared helpers live here at the repo
root, importable with no dependencies at all.
"""

from __future__ import annotations

import json


def load_runs(path: str) -> list:
    """The artifact's runs list; [] for a missing/malformed file (the
    artifact reader must not be able to fail the bench reporting on it)."""
    try:
        with open(path) as f:
            prev = json.load(f)
        if isinstance(prev, dict) and isinstance(prev.get("runs"), list):
            return prev["runs"]
    except (OSError, ValueError):
        pass
    return []


def merge_runs(path: str, entry: dict, meta: dict,
               keep_runs: int = 20) -> dict:
    """Append one row (newest LAST, history bounded) and rewrite the
    artifact — telemetry.run.merge_artifact's semantics, jax-free."""
    data = {"runs": load_runs(path)}
    data["runs"] = (data["runs"] + [{**entry, **meta}])[-keep_runs:]
    with open(path, "w") as f:
        json.dump(data, f, indent=1)
    return data


def same_config(a: dict, b: dict, keys) -> bool:
    """Rows are fence-comparable only when every identity key matches —
    rows measured under different shapes/models/backends never are."""
    return all(a.get(k) == b.get(k) for k in keys)
